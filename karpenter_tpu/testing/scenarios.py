"""Benchmark scenario generators.

``diverse_pods`` mirrors the reference benchmark's pod mix
(``scheduling_benchmark_test.go:159-216``): 1/7 each of generic,
zone-topology-spread, hostname-topology-spread, pod-affinity (hostname),
pod-affinity (zone), pod-anti-affinity (hostname), pod-anti-affinity (zone),
with the same randomized label/cpu/memory pools.
"""

from __future__ import annotations

import random
from typing import List, Optional

from karpenter_tpu.api import labels as lbl
from karpenter_tpu.api.objects import LabelSelector, Pod, PodAffinityTerm
from karpenter_tpu.testing.factories import hostname_spread, make_pod, zone_spread

_LABEL_VALUES = ["a", "b", "c", "d", "e", "f", "g"]
_MEM_MI = [100, 256, 512, 1024, 2048, 4096]
_CPU_M = [100, 250, 500, 1000, 1500]


def _random_labels(rng: random.Random) -> dict:
    return {"my-label": rng.choice(_LABEL_VALUES)}


def _requests(rng: random.Random) -> dict:
    return {
        "cpu": f"{rng.choice(_CPU_M)}m",
        "memory": f"{rng.choice(_MEM_MI)}Mi",
    }


def diverse_pods(count: int, rng: Optional[random.Random] = None) -> List[Pod]:
    rng = rng or random.Random(42)
    pods: List[Pod] = []
    seventh = count // 7

    for _ in range(seventh):  # generic
        pods.append(make_pod(labels=_random_labels(rng), requests=_requests(rng)))
    for key, builder in ((lbl.TOPOLOGY_ZONE, zone_spread), (lbl.HOSTNAME, hostname_spread)):
        for _ in range(seventh):  # topology spread
            sel = _random_labels(rng)
            pods.append(
                make_pod(
                    labels=sel,
                    requests=_requests(rng),
                    topology=[builder(max_skew=1, labels=sel)],
                )
            )
    for key in (lbl.HOSTNAME, lbl.TOPOLOGY_ZONE):  # pod affinity
        for _ in range(seventh):
            pods.append(
                make_pod(
                    labels=_random_labels(rng),
                    requests=_requests(rng),
                    pod_requirements=[
                        PodAffinityTerm(
                            label_selector=LabelSelector(match_labels=_random_labels(rng)),
                            topology_key=key,
                        )
                    ],
                )
            )
    for key in (lbl.HOSTNAME, lbl.TOPOLOGY_ZONE):  # pod anti-affinity
        for _ in range(seventh):
            pods.append(
                make_pod(
                    labels=_random_labels(rng),
                    requests=_requests(rng),
                    pod_anti_requirements=[
                        PodAffinityTerm(
                            label_selector=LabelSelector(match_labels=_random_labels(rng)),
                            topology_key=key,
                        )
                    ],
                )
            )
    while len(pods) < count:  # fill remainder with generic pods
        pods.append(make_pod(labels=_random_labels(rng), requests=_requests(rng)))
    return pods


def affinity_dense_pods(
    count: int,
    rng: Optional[random.Random] = None,
    frac: float = 0.5,
    group_size: int = 20,
) -> List[Pod]:
    """The affinity-dense regime (VERDICT r5 #1b): ``frac`` of the batch
    carries REQUIRED pod-(anti-)affinity across ``count*frac/group_size``
    distinct groups — the shape that maximizes the topology pre-assignment
    pass relative to the pack itself. Every 4th group is hostname
    anti-affinity (one pod per node, the most constrained rule); the rest
    are zone affinity (co-locate the group)."""
    rng = rng or random.Random(42)
    n_aff = int(count * frac)
    pods: List[Pod] = []
    g = 0
    while len(pods) < n_aff:
        sel = {"aff-group": f"g{g}"}
        if g % 4 == 3:
            term = dict(
                pod_anti_requirements=[
                    PodAffinityTerm(
                        label_selector=LabelSelector(match_labels=sel),
                        topology_key=lbl.HOSTNAME,
                    )
                ]
            )
        else:
            term = dict(
                pod_requirements=[
                    PodAffinityTerm(
                        label_selector=LabelSelector(match_labels=sel),
                        topology_key=lbl.TOPOLOGY_ZONE,
                    )
                ]
            )
        for _ in range(min(group_size, n_aff - len(pods))):
            pods.append(make_pod(labels=sel, requests=_requests(rng), **term))
        g += 1
    while len(pods) < count:
        pods.append(make_pod(labels=_random_labels(rng), requests=_requests(rng)))
    rng.shuffle(pods)
    return pods
