"""Object factories for tests — the analog of ``pkg/test``'s option-struct
factories (pods.go, nodes.go, daemonsets.go, storage.go)."""

from __future__ import annotations

import itertools
from typing import Dict, List, Optional

from karpenter_tpu.api import labels as lbl
from karpenter_tpu.api.objects import (
    Affinity,
    Container,
    DaemonSet,
    LabelSelector,
    NodeAffinity,
    NodeSelectorRequirement,
    NodeSelectorTerm,
    ObjectMeta,
    OwnerReference,
    Pod,
    PodAffinity,
    PodAffinityTerm,
    PodAntiAffinity,
    PodCondition,
    PodSpec,
    PodStatus,
    PreferredSchedulingTerm,
    Toleration,
    TopologySpreadConstraint,
)
from karpenter_tpu.api.provisioner import Constraints, Limits, Provisioner, ProvisionerSpec
from karpenter_tpu.api.requirements import Requirements
from karpenter_tpu.utils import resources as res

_counter = itertools.count(1)


def make_pod(
    name: Optional[str] = None,
    namespace: str = "default",
    labels: Optional[Dict[str, str]] = None,
    requests: Optional[Dict[str, object]] = None,
    limits: Optional[Dict[str, object]] = None,
    node_selector: Optional[Dict[str, str]] = None,
    node_requirements: Optional[List[NodeSelectorRequirement]] = None,
    node_preferences: Optional[List[PreferredSchedulingTerm]] = None,
    pod_requirements: Optional[List[PodAffinityTerm]] = None,
    pod_anti_requirements: Optional[List[PodAffinityTerm]] = None,
    tolerations: Optional[List[Toleration]] = None,
    topology: Optional[List[TopologySpreadConstraint]] = None,
    node_name: str = "",
    unschedulable: bool = True,
    owner: Optional[OwnerReference] = None,
    priority_class_name: str = "",
) -> Pod:
    affinity = None
    if node_requirements or node_preferences or pod_requirements or pod_anti_requirements:
        affinity = Affinity()
        if node_requirements or node_preferences:
            affinity.node_affinity = NodeAffinity(
                required=[NodeSelectorTerm(match_expressions=list(node_requirements or []))]
                if node_requirements
                else [],
                preferred=list(node_preferences or []),
            )
        if pod_requirements:
            affinity.pod_affinity = PodAffinity(required=list(pod_requirements))
        if pod_anti_requirements:
            affinity.pod_anti_affinity = PodAntiAffinity(required=list(pod_anti_requirements))
    status = PodStatus()
    if unschedulable and not node_name:
        status.conditions.append(
            PodCondition(type="PodScheduled", status="False", reason="Unschedulable")
        )
    return Pod(
        metadata=ObjectMeta(
            name=name or f"pod-{next(_counter)}", namespace=namespace,
            labels=dict(labels or {}),
            owner_references=[owner] if owner is not None else [],
        ),
        spec=PodSpec(
            node_name=node_name,
            node_selector=dict(node_selector or {}),
            affinity=affinity,
            tolerations=list(tolerations or []),
            containers=[
                Container(
                    requests=res.parse_resource_list(requests),
                    limits=res.parse_resource_list(limits),
                )
            ],
            topology_spread_constraints=list(topology or []),
            priority_class_name=priority_class_name,
        ),
        status=status,
    )


def make_provisioner(
    name: str = "default",
    labels: Optional[Dict[str, str]] = None,
    taints=None,
    requirements: Optional[List[NodeSelectorRequirement]] = None,
    limits: Optional[Dict[str, object]] = None,
    solver: str = "ffd",
    ttl_after_empty: Optional[int] = None,
    ttl_until_expired: Optional[int] = None,
    provider: Optional[Dict] = None,
) -> Provisioner:
    return Provisioner(
        metadata=ObjectMeta(name=name, namespace=""),
        spec=ProvisionerSpec(
            constraints=Constraints(
                labels=dict(labels or {}),
                taints=list(taints or []),
                requirements=Requirements.new(*(requirements or [])),
                provider=provider,
            ),
            limits=Limits(resources=res.parse_resource_list(limits)) if limits else None,
            solver=solver,
            ttl_seconds_after_empty=ttl_after_empty,
            ttl_seconds_until_expired=ttl_until_expired,
        ),
    )


def make_daemonset(
    name: Optional[str] = None,
    requests: Optional[Dict[str, object]] = None,
    node_selector: Optional[Dict[str, str]] = None,
    tolerations: Optional[List[Toleration]] = None,
) -> DaemonSet:
    return DaemonSet(
        metadata=ObjectMeta(name=name or f"ds-{next(_counter)}", namespace="kube-system"),
        pod_template=PodSpec(
            node_selector=dict(node_selector or {}),
            tolerations=list(tolerations or []),
            containers=[Container(requests=res.parse_resource_list(requests))],
        ),
    )


def make_node(
    name: Optional[str] = None,
    labels: Optional[Dict[str, str]] = None,
    capacity: Optional[Dict[str, object]] = None,
    allocatable: Optional[Dict[str, object]] = None,
    taints=None,
    ready: bool = True,
    provisioner_name: Optional[str] = None,
    finalizers: Optional[List[str]] = None,
):
    """reference: pkg/test/nodes.go."""
    from karpenter_tpu.api.objects import Node, NodeSpec, NodeStatus

    node_labels = dict(labels or {})
    if provisioner_name is not None:
        node_labels[lbl.PROVISIONER_NAME_LABEL] = provisioner_name
    cap = res.parse_resource_list(capacity)
    return Node(
        metadata=ObjectMeta(
            name=name or f"node-{next(_counter)}",
            namespace="",
            labels=node_labels,
            finalizers=list(finalizers or []),
        ),
        spec=NodeSpec(taints=list(taints or [])),
        status=NodeStatus(
            capacity=cap,
            allocatable=res.parse_resource_list(allocatable) or dict(cap),
            conditions=[
                PodCondition(type="Ready", status="True" if ready else "False")
            ],
        ),
    )


def make_pvc(
    name: Optional[str] = None,
    namespace: str = "default",
    storage_class: str = "",
    volume_name: str = "",
):
    from karpenter_tpu.api.objects import PersistentVolumeClaim

    return PersistentVolumeClaim(
        metadata=ObjectMeta(name=name or f"pvc-{next(_counter)}", namespace=namespace),
        storage_class_name=storage_class,
        volume_name=volume_name,
    )


def make_pv(name: Optional[str] = None, zones: Optional[List[str]] = None):
    from karpenter_tpu.api.objects import PersistentVolume

    terms = []
    if zones:
        terms = [
            NodeSelectorTerm(
                match_expressions=[
                    NodeSelectorRequirement(key=lbl.TOPOLOGY_ZONE, operator="In", values=list(zones))
                ]
            )
        ]
    return PersistentVolume(
        metadata=ObjectMeta(name=name or f"pv-{next(_counter)}", namespace=""),
        node_affinity_required=terms,
    )


def make_storage_class(name: Optional[str] = None, zones: Optional[List[str]] = None):
    from karpenter_tpu.api.objects import StorageClass

    terms = []
    if zones:
        terms = [
            NodeSelectorTerm(
                match_expressions=[
                    NodeSelectorRequirement(key=lbl.TOPOLOGY_ZONE, operator="In", values=list(zones))
                ]
            )
        ]
    return StorageClass(
        metadata=ObjectMeta(name=name or f"sc-{next(_counter)}", namespace=""),
        allowed_topologies=terms,
    )


def make_pdb(
    name: Optional[str] = None,
    namespace: str = "default",
    labels: Optional[Dict[str, str]] = None,
    min_available: Optional[int] = None,
    max_unavailable: Optional[int] = None,
):
    from karpenter_tpu.api.objects import PodDisruptionBudget

    return PodDisruptionBudget(
        metadata=ObjectMeta(name=name or f"pdb-{next(_counter)}", namespace=namespace),
        selector=LabelSelector(match_labels=dict(labels or {})),
        min_available=min_available,
        max_unavailable=max_unavailable,
    )


def zone_spread(max_skew: int = 1, labels: Optional[Dict[str, str]] = None) -> TopologySpreadConstraint:
    return TopologySpreadConstraint(
        max_skew=max_skew,
        topology_key=lbl.TOPOLOGY_ZONE,
        when_unsatisfiable="DoNotSchedule",
        label_selector=LabelSelector(match_labels=dict(labels or {})),
    )


def hostname_spread(max_skew: int = 1, labels: Optional[Dict[str, str]] = None) -> TopologySpreadConstraint:
    return TopologySpreadConstraint(
        max_skew=max_skew,
        topology_key=lbl.HOSTNAME,
        when_unsatisfiable="DoNotSchedule",
        label_selector=LabelSelector(match_labels=dict(labels or {})),
    )
