"""Test/benchmark object factories — a first-class deliverable, mirroring the
reference's pkg/test (pods.go, nodes.go, daemonsets.go, storage.go)."""
from karpenter_tpu.testing.factories import (  # noqa: F401
    hostname_spread,
    make_daemonset,
    make_pod,
    make_provisioner,
    zone_spread,
)
from karpenter_tpu.testing.chaos import (  # noqa: F401
    ChaosPolicy,
    ChaosProxy,
    ChaosWindow,
    chaos_wrap,
)
from karpenter_tpu.testing.scenarios import (  # noqa: F401
    affinity_dense_pods,
    diverse_pods,
)
