"""Fleet-scale horizontal availability (docs/fleet.md).

Provisioners are partitioned across N controller replicas by per-shard
leases (``utils.lease.FileLeaseSet`` / ``kube.leader.KubeLeaseSet``):
each replica heartbeats its membership, claims the shards rendezvous
hashing assigns it among the live members, and renews them on a cadence.
A replica that stops renewing loses every shard within one lease duration
and survivors take them over — losing a replica degrades capacity, never
availability.
"""

from karpenter_tpu.fleet.ownership import (
    DEFAULT_SHARD,
    ShardManager,
    WatchedShardKeys,
    build_lease_set,
    rendezvous_owner,
)

__all__ = [
    "DEFAULT_SHARD",
    "ShardManager",
    "WatchedShardKeys",
    "build_lease_set",
    "rendezvous_owner",
]
