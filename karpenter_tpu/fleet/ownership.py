"""Per-provisioner shard ownership across controller replicas.

``ShardManager`` generalizes ``LeaderElector``'s active/passive contract to
a KEYED lease set: instead of one leader owning everything, each replica
owns the subset of provisioner shards that rendezvous hashing assigns it
among the live members, and the lease set arbitrates races (flock CAS for
``FileLeaseSet``, apiserver optimistic concurrency for ``KubeLeaseSet``).

The safety property mirrors ``LeaderElector.on_lost`` per shard: a failed
renewal fires ``on_lost(key)`` exactly once per holding epoch and the
replica must stop mutating that provisioner's cloud state BEFORE the lease
duration elapses and a survivor claims the shard. The liveness property is
rebalance-on-death: a crashed replica's membership and shard holds expire
together, the rendezvous placement re-ranks every orphaned key over the
survivors, and each survivor claims its share on the next tick — so the
whole fleet re-converges within ~2 lease durations (the acceptance bar the
chaos replica-kill scenario holds it to).

A claim is taken immediately when this replica IS the rendezvous winner;
a key whose winner is some other live member is left alone for one full
tick (``_pending_claims``) so the winner gets first chance — only if it
stays unheld (a wedged-but-heartbeating winner) does a loser steal it.
"""

from __future__ import annotations

import hashlib
import logging
import threading
from typing import Callable, Dict, Iterable, Optional, Set

from karpenter_tpu import metrics
from karpenter_tpu.utils.lease import DEFAULT_RENEW_INTERVAL

logger = logging.getLogger("karpenter.fleet")

# the shard for work with no provisioner attribution (nodes without a
# provisioner label, cluster-scoped chores): always part of the key
# universe so exactly one replica handles it
DEFAULT_SHARD = "__unassigned__"


def rendezvous_owner(key: str, members: Iterable[str]) -> Optional[str]:
    """Highest-random-weight (rendezvous) placement: the member whose
    ``blake2b(member ## key)`` scores highest owns the key. Deterministic
    for every observer sharing the member view, and minimally disruptive —
    a member's death re-homes ONLY its own keys."""
    best, best_score = None, b""
    for member in members:
        score = hashlib.blake2b(
            f"{member}##{key}".encode(), digest_size=8
        ).digest()
        if best is None or score > best_score:
            best, best_score = member, score
    return best


def build_lease_set(spec: str, cluster=None, identity: Optional[str] = None,
                    duration: Optional[float] = None):
    """``kube:<namespace>/<prefix>`` → :class:`KubeLeaseSet` (requires a
    cluster that actually coordinates replicas); anything else is a shared
    file path → :class:`FileLeaseSet`."""
    kwargs = {}
    if identity:
        kwargs["identity"] = identity
    if duration:
        kwargs["duration"] = duration
    if spec.startswith("kube:"):
        from karpenter_tpu.kube.leader import KubeLeaseSet

        ns_prefix = spec[len("kube:"):]
        if "/" in ns_prefix:
            namespace, _, prefix = ns_prefix.partition("/")
        else:
            namespace, prefix = "kube-system", ns_prefix
        return KubeLeaseSet(
            cluster,
            prefix=prefix or "karpenter-shard",
            namespace=namespace or "kube-system",
            **kwargs,
        )
    from karpenter_tpu.utils.lease import FileLeaseSet

    return FileLeaseSet(spec, **kwargs)


class WatchedShardKeys:
    """Informer-watch-driven shard-key discovery (ROADMAP item 3 headroom).

    The first fleet cut passed ``keys_fn=lambda: [...cluster.provisioners()]``
    — one provisioner LIST per replica per renew interval, paid forever at
    fleet scale. This source seeds once and then maintains the key set from
    the cluster's provisioner watch events; a membership change (a
    provisioner added or deleted) additionally fires ``on_change`` so the
    ShardManager ticks IMMEDIATELY — a new provisioner gets an owner within
    one watch delivery, not one renew interval."""

    def __init__(self, cluster):
        self._mu = threading.Lock()
        self._keys: Set[str] = set()  # guarded-by: self._mu
        # fired (outside the lock) when the key set actually changed;
        # wired to ShardManager.request_tick by build_runtime
        self.on_change: Optional[Callable[[], None]] = None
        # watch BEFORE the seed list: an event landing between the two is
        # applied on top of the union'd seed instead of being lost
        cluster.watch("provisioners", self._on_event)
        with self._mu:
            self._keys |= {p.metadata.name for p in cluster.provisioners()}

    def _on_event(self, event: str, obj) -> None:
        name = obj.metadata.name
        gone = event == "DELETED" or obj.metadata.deletion_timestamp is not None
        with self._mu:
            before = name in self._keys
            if gone:
                self._keys.discard(name)
            else:
                self._keys.add(name)
            changed = (name in self._keys) != before
        if changed and self.on_change is not None:
            try:
                self.on_change()
            except Exception:
                logger.exception("shard-key change notification failed")

    def keys(self) -> Set[str]:
        with self._mu:
            return set(self._keys)


class ShardManager:
    """One replica's view of the fleet: which shards it owns right now.

    ``tick()`` is the whole protocol — heartbeat membership, renew owned
    shards (lost renewals fire ``on_lost`` and drop ownership), release
    shards whose key left the universe, then claim desired keys this
    replica wins under rendezvous placement (or steals after the winner
    left them unheld for a full tick). The background thread just calls
    ``tick()`` on the renew cadence; tests drive it synchronously.

    ``owns(key)`` is the hot-path read every reconcile and launch guard
    makes — a set lookup under a mutex, no I/O."""

    def __init__(
        self,
        leases,
        keys_fn: Callable[[], Iterable[str]],
        renew_interval: Optional[float] = None,
        on_acquired: Optional[Callable[[str], None]] = None,
        on_lost: Optional[Callable[[str], None]] = None,
        include_default_shard: bool = True,
    ):
        self.leases = leases
        self.keys_fn = keys_fn
        # derive from the lease duration unless overridden: a renew cadence
        # slower than the duration would expire every hold between ticks
        # (continuous on_lost/on_acquired churn, the fleet never converges)
        if renew_interval is None:
            duration = getattr(leases, "duration", DEFAULT_RENEW_INTERVAL * 3)
            renew_interval = min(DEFAULT_RENEW_INTERVAL, duration / 3.0)
        self.renew_interval = renew_interval
        self.on_acquired = on_acquired
        self.on_lost = on_lost
        self.include_default_shard = include_default_shard
        self.identity = leases.identity
        self._mu = threading.Lock()
        self._owned: Set[str] = set()  # guarded-by: self._mu
        # keys observed unheld last tick whose rendezvous winner is another
        # live member — steal candidates if still unheld this tick
        self._pending_claims: Set[str] = set()  # guarded-by: self._mu
        self._stop = threading.Event()
        self._crashed = threading.Event()  # chaos: die without releasing
        # set by request_tick(): the run loop wakes early instead of
        # sleeping out the renew interval (a provisioner appearing should
        # find an owner within one watch delivery, docs/fleet.md)
        self._wake = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # key -> last live holder observed in any snapshot; a claim of a
        # key last seen held by a DIFFERENT replica is a takeover
        # (rebalance-on-death), counted separately from first claims
        self._last_seen_holder: Dict[str, str] = {}  # guarded-by: self._mu
        # key -> the rendezvous winner it was STOLEN from (the winner was
        # live but left the key unheld for a full tick — wedged). The
        # handback loop must not release such a key back to the SAME
        # winner, or steal and handback would oscillate every ~2 ticks
        # with the shard's worker bouncing; the entry clears when the
        # key's winner changes (membership change) or the key is lost.
        self._stolen_from: Dict[str, str] = {}  # guarded-by: self._mu
        # observability for tests/bench: monotonic tick counter and the
        # last tick's membership view
        self.ticks = 0  # guarded-by: self._mu
        self.last_members: Set[str] = set()  # guarded-by: self._mu

    # -- reads --------------------------------------------------------------
    def owns(self, key: str) -> bool:
        with self._mu:
            return key in self._owned

    def owned(self) -> Set[str]:
        with self._mu:
            return set(self._owned)

    def fenced(self) -> bool:
        """Is this replica FENCED — the lease backend unreachable past a
        held lease's expiry margin (docs/partition.md)? While True, the
        launch guards and the GC sweep refuse cloud creates/terminates:
        a peer with a working control plane may legitimately own our
        shards already. Backends without the concept (``FileLeaseSet``)
        never fence."""
        fn = getattr(self.leases, "fenced", None)
        if fn is None:
            return False
        try:
            return bool(fn())
        except Exception:
            logger.exception("fence status read failed")
            return False

    # -- the protocol -------------------------------------------------------
    def tick(self) -> None:
        """One claim/renew/release round. Exceptions from the lease backend
        surface to the caller (the run loop contains them; a raising
        backend mid-tick loses nothing — un-renewed holds simply expire).
        Re-checks ``_stop`` at each phase: a tick wedged in a slow backend
        can outlive ``stop()``'s join timeout, and its claim loop must not
        re-acquire the leases stop just released (a dead replica holding
        every shard for a full lease duration)."""
        if self._stop.is_set():
            return
        members = set(self.leases.heartbeat())
        desired = set(self.keys_fn())
        if self.include_default_shard:
            desired.add(DEFAULT_SHARD)

        with self._mu:
            owned = set(self._owned)

        # renew first: holding is useless if the lease lapses mid-tick
        renewed = self.leases.renew_many(owned) if owned else set()
        for key in owned - renewed:
            self._lose(key)
        owned = renewed

        # release shards whose key left the universe (provisioner deleted).
        # on_lost FIRST (stops the worker synchronously), release SECOND —
        # the same no-two-concurrent-owners ordering as handback/stop: a
        # deleted-then-recreated provisioner's key must not be claimable
        # by a peer while this replica's launch is still in flight.
        for key in owned - desired:
            self._lose(key, reason="deleted")
            self.leases.release(key)
        owned &= desired

        # graceful handback: a shard whose rendezvous winner among the LIVE
        # members is another replica migrates there (a new replica joining
        # an up fleet must drain its share off the incumbents, or the first
        # replica keeps everything forever). on_lost stops the worker FIRST,
        # then the lease releases — the winner claims it next tick, so a
        # handback costs one tick of that shard being idle, never two
        # concurrent owners. A key STOLEN from a wedged-but-heartbeating
        # winner is exempt while that same member stays the winner —
        # releasing it back would just re-orphan it (steal/handback
        # oscillation); a membership change re-enables normal placement.
        handed_back: Set[str] = set()
        for key in sorted(owned):
            winner = rendezvous_owner(key, members)
            if winner == self.identity:
                continue
            with self._mu:
                stolen_from = self._stolen_from.get(key)
                if stolen_from is not None and stolen_from != winner:
                    del self._stolen_from[key]  # winner changed: normal rules
                    stolen_from = None
            if stolen_from == winner:
                continue
            self._lose(key, reason="handback")
            self.leases.release(key)
            owned.discard(key)
            handed_back.add(key)

        # claim: winners immediately, losers only steal keys that stayed
        # unheld across a full tick (the winner had its chance). The
        # desired keys are passed so the kube backend can resolve holders
        # for keys THIS replica never touched (its lazy lease table only
        # knows touched keys; FileLeaseSet ignores the hint).
        snapshot = self.leases.snapshot(sorted(desired))
        with self._mu:
            self._last_seen_holder.update(snapshot)
            # forget holders of keys that left the universe
            for key in list(self._last_seen_holder):
                if key not in desired:
                    del self._last_seen_holder[key]
        next_pending: Set[str] = set()
        for key in sorted(desired - owned):
            if self._stop.is_set():
                return  # stop() released our leases; claiming now would re-take them
            if key in handed_back:
                # just released to its winner THIS tick: neither claim nor
                # mark pending — the winner gets two full ticks before the
                # loser-steal clock starts, or a merely-slow (not wedged)
                # winner would lose the key right back and _stolen_from
                # would pin the misplacement until membership changes
                continue
            holder = snapshot.get(key)
            if holder is not None and holder != self.identity:
                continue  # live hold by a peer
            winner = rendezvous_owner(key, members)
            with self._mu:
                may_steal = key in self._pending_claims
                previous = self._last_seen_holder.get(key)
            if winner != self.identity and not may_steal:
                next_pending.add(key)
                continue
            if self.leases.try_acquire(key):
                if winner != self.identity:
                    # stolen from a live-but-wedged winner: exempt from
                    # handback while that member stays the winner
                    with self._mu:
                        self._stolen_from[key] = winner
                self._gain(
                    key,
                    taken_over=previous is not None and previous != self.identity,
                )
        with self._mu:
            self._pending_claims = next_pending
            self.ticks += 1
            self.last_members = members
            metrics.FLEET_SHARDS_OWNED.set(len(self._owned))
        metrics.FLEET_FENCED.set(1 if self.fenced() else 0)

    def _gain(self, key: str, taken_over: bool = False) -> None:
        with self._mu:
            if key in self._owned:
                return
            self._owned.add(key)
        if taken_over:
            metrics.FLEET_REBALANCES.inc()
        logger.info(
            "shard %s acquired by %s%s", key, self.identity,
            " (takeover)" if taken_over else "",
        )
        if self.on_acquired is not None:
            try:
                self.on_acquired(key)
            except Exception:
                logger.exception("on_acquired(%s) failed", key)

    def _lose(self, key: str, reason: str = "lost") -> None:
        with self._mu:
            if key not in self._owned:
                return
            self._owned.discard(key)
            self._stolen_from.pop(key, None)
        if reason == "lost":
            metrics.FLEET_SHARD_LOSSES.inc()
            logger.warning("shard %s lease lost by %s", key, self.identity)
        else:
            logger.info("shard %s released by %s (%s)", key, self.identity, reason)
        if self.on_lost is not None:
            try:
                self.on_lost(key)
            except Exception:
                logger.exception("on_lost(%s) failed", key)

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="shard-manager"
        )
        self._thread.start()

    def request_tick(self) -> None:
        """Wake the run loop for an immediate tick (key-universe change
        from the informer watch, a test nudging convergence). Safe from
        any thread; a no-op when the background loop isn't running."""
        self._wake.set()

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.tick()
            except Exception:
                # a raising lease backend must not kill the manager thread;
                # un-renewed holds expire on their own — the safe direction
                logger.exception("shard tick failed")
            self._wake.wait(self.renew_interval)
            self._wake.clear()

    def crash(self) -> None:
        """Chaos hook: die WITHOUT releasing — holds and membership expire
        on the lease duration, exactly like a SIGKILL'd replica."""
        self._crashed.set()
        self._stop.set()
        self._wake.set()  # a loop parked in its inter-tick wait dies now
        if self._thread:
            self._thread.join(timeout=2)
        with self._mu:
            self._owned.clear()

    def stop(self) -> None:
        self._stop.set()
        self._wake.set()
        if self._thread:
            self._thread.join(timeout=2)
        if self._crashed.is_set():
            return  # crashed: leave the leases to expire
        with self._mu:
            owned = set(self._owned)
            self._owned.clear()
        for key in owned:
            # on_lost FIRST (it stops the shard's worker synchronously),
            # release SECOND — the same ordering the handback path keeps:
            # a survivor claiming the released lease must never overlap a
            # launch this replica still has in flight
            if self.on_lost is not None:
                try:
                    self.on_lost(key)
                except Exception:
                    logger.exception("on_lost(%s) failed", key)
            try:
                self.leases.release(key)
            except Exception:
                logger.exception("releasing shard %s failed", key)
        try:
            self.leases.resign()
        except Exception:
            logger.exception("membership resign failed (expires on its own)")
