"""Cloud-provider registry — the build-tag switch analog.

The reference selects its vendor at compile time (``registry/aws.go``
``//go:build aws`` vs ``registry/fake.go``); here the selection is by name at
process start (reference: registry/register.go:24-37). Registering installs
the vendor's Default/Validate hooks, which the webhook and the provisioning
controller both call.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional

from karpenter_tpu.cloudprovider.types import CloudProvider

_FACTORIES: Dict[str, Callable[[], CloudProvider]] = {}


def register(name: str, factory: Callable[[], CloudProvider]) -> None:
    _FACTORIES[name] = factory


def new_cloud_provider(name: str = "fake", **kwargs) -> CloudProvider:
    factory = _FACTORIES.get(name)
    if factory is None:
        raise ValueError(f"unknown cloud provider {name!r}; registered: {sorted(_FACTORIES)}")
    return factory(**kwargs) if kwargs else factory()


def _register_builtins() -> None:
    from karpenter_tpu.cloudprovider.fake import FakeCloudProvider
    from karpenter_tpu.cloudprovider.gke import GkeCloudProvider
    from karpenter_tpu.cloudprovider.simulated import SimulatedCloudProvider

    register("fake", FakeCloudProvider)
    register("simulated", SimulatedCloudProvider)
    register("gke", GkeCloudProvider)


_register_builtins()
