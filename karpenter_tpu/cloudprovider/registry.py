"""Cloud-provider registry — the build-tag switch analog.

The reference selects its vendor at compile time (``registry/aws.go``
``//go:build aws`` vs ``registry/fake.go``); here the selection is by name at
process start (reference: registry/register.go:24-37). Registering installs
the vendor's Default/Validate hooks, which the webhook and the provisioning
controller both call.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional

from karpenter_tpu.cloudprovider.types import CloudProvider

_FACTORIES: Dict[str, Callable[[], CloudProvider]] = {}


def register(name: str, factory: Callable[[], CloudProvider]) -> None:
    _FACTORIES[name] = factory


def new_cloud_provider(name: str = "fake", **kwargs) -> CloudProvider:
    factory = _FACTORIES.get(name)
    if factory is None:
        raise ValueError(f"unknown cloud provider {name!r}; registered: {sorted(_FACTORIES)}")
    return factory(**kwargs) if kwargs else factory()


def _register_builtins() -> None:
    from karpenter_tpu.cloudprovider.fake import FakeCloudProvider
    from karpenter_tpu.cloudprovider.gke import GkeCloudProvider
    from karpenter_tpu.cloudprovider.simulated import SimulatedCloudProvider

    register("fake", FakeCloudProvider)
    register("simulated", SimulatedCloudProvider)
    register("gke", GkeCloudProvider)

    def _resolve_base(url: str, name: str) -> str:
        # control plane over the wire: --cloud-provider=<name>-http with
        # KARPENTER_CLOUD_API_URL (or the url kwarg) pointing at a server
        # speaking the cloudprovider/httpapi.py REST protocol
        import os

        base = url or os.environ.get("KARPENTER_CLOUD_API_URL", "")
        if not base:
            raise ValueError(f"{name} needs KARPENTER_CLOUD_API_URL (or url=...)")
        return base

    def _http_simulated(url: str = "") -> CloudProvider:
        from karpenter_tpu.cloudprovider.httpapi import HttpCloudAPI

        return SimulatedCloudProvider(
            HttpCloudAPI(_resolve_base(url, "simulated-http"))
        )

    def _http_gke(url: str = "") -> CloudProvider:
        from karpenter_tpu.cloudprovider.httpapi import HttpGkeAPI

        return GkeCloudProvider(api=HttpGkeAPI(_resolve_base(url, "gke-http")))

    register("simulated-http", _http_simulated)
    register("gke-http", _http_gke)


_register_builtins()
