"""HTTP wire protocol for the simulated cloud control plane.

VERDICT r3 ask #7: the vendor layer used to call ``SimCloudAPI`` as
in-process Python functions, so the client and its double could share a
protocol misunderstanding. This module puts a REAL wire between them,
the way the reference's provider drives an SDK over HTTP against
behavior-programmable fakes (reference: aws/fake/ec2api.go:35-137):

- ``CloudAPIServer`` serves a ``SimCloudAPI`` (or ``SimGkeAPI``-style
  object) over REST: JSON bodies, list pagination with opaque
  next-tokens, structured error bodies ``{"error": {"code", "message"}}``,
  throttling as 429 + Retry-After, injected control-plane failures as
  5xx. Tests keep programming the underlying ``SimCloudAPI`` directly
  (same process) — the *calls* cross HTTP.
- ``HttpCloudAPI`` is the client: same eight-method protocol as
  ``SimCloudAPI`` (drop-in for ``SimulatedCloudProvider(api=...)``),
  implemented over urllib with bounded retries — exponential backoff on
  5xx, Retry-After-honoring retries on 429 — pagination loops, and error
  classification from the wire error code back to the typed exceptions
  the providers already handle (``InsufficientCapacityError``,
  ``CloudAPIError``).
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from dataclasses import asdict
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional, Sequence, Tuple

from karpenter_tpu.cloudprovider.simulated import (
    CloudAPIError,
    InstanceNotFoundError,
    InsufficientCapacityError,
    SimCloudAPI,
    SimInstance,
    SimInstanceTypeInfo,
    SimSecurityGroup,
    SimSubnet,
)
from karpenter_tpu.interruption.types import DisruptionNotice

# wire error codes (the EC2-style error-code vocabulary the reference's
# error classifier switches on — aws/errors.go)
CODE_ICE = "InsufficientInstanceCapacity"
CODE_THROTTLE = "RequestLimitExceeded"
CODE_INTERNAL = "InternalError"
CODE_NOT_FOUND = "NotFound"  # route-level: unknown method+path
CODE_INSTANCE_NOT_FOUND = "InvalidInstanceID.NotFound"  # typed: no such record
CODE_BAD_REQUEST = "InvalidArgument"

DEFAULT_PAGE_SIZE = 3  # small so real catalogs actually paginate in tests


class ThrottlingError(Exception):
    """Injectable control-plane throttle: the server answers 429 with a
    Retry-After header; the HTTP client retries, in-process callers see
    the raised exception directly."""

    def __init__(self, retry_after: float = 0.05):
        super().__init__(f"throttled, retry after {retry_after}s")
        self.retry_after = retry_after


class _BadRequest(Exception):
    """Malformed wire request (missing field, invalid JSON) → 400, which
    the client classifies as a deterministic error and never retries."""


# ---------------------------------------------------------------------------
# server
# ---------------------------------------------------------------------------


class _JsonApiServer:
    """Shared scaffolding: a localhost ThreadingHTTPServer whose handler
    maps the double's typed exceptions to wire status codes + error
    bodies. Subclasses implement ``_route``."""

    def __init__(self):
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def _send(self, status: int, body: Dict[str, Any], headers=()):
                data = json.dumps(body).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                for k, v in headers:
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(data)

            def _error(self, status: int, code: str, message: str, headers=(),
                       details=None):
                body: Dict[str, Any] = {"error": {"code": code, "message": message}}
                if details:
                    body["error"]["details"] = details
                self._send(status, body, headers)

            def _body(self) -> Dict[str, Any]:
                length = int(self.headers.get("Content-Length", 0))
                try:
                    return json.loads(self.rfile.read(length) or b"{}")
                except json.JSONDecodeError as e:
                    raise _BadRequest(f"invalid JSON body: {e}") from e

            def _dispatch(self, method: str):
                try:
                    # traceparent-style propagation: a traced client call
                    # (the metered provider's cloud.<method> span) parents
                    # this server's request span, so the control plane's
                    # share of a launch is attributable in one trace
                    from karpenter_tpu import obs

                    ctx = obs.from_traceparent(self.headers.get("traceparent"))
                    if ctx is not None:
                        with obs.tracer().span(
                            "cloudapi.request",
                            parent=ctx,
                            attrs={"method": method, "path": self.path},
                        ):
                            outer._route(self, method)
                    else:
                        outer._route(self, method)
                except ThrottlingError as e:
                    self._error(429, CODE_THROTTLE, str(e),
                                headers=[("Retry-After", f"{e.retry_after:.3f}")])
                except InsufficientCapacityError as e:
                    # the all-ICE fleet outcome crosses the wire typed, WITH
                    # its errored overrides, so the client-side ICE cache
                    # marks exactly the pools the server saw exhausted
                    details = None
                    if getattr(e, "overrides", None):
                        details = {"overrides": [
                            {"capacityType": ct, "instanceType": it, "zone": z}
                            for ct, it, z in e.overrides
                        ]}
                    self._error(409, CODE_ICE, str(e), details=details)
                except _BadRequest as e:
                    self._error(400, CODE_BAD_REQUEST, str(e))
                except InstanceNotFoundError as e:
                    # BEFORE the CloudAPIError catch-all (it subclasses it):
                    # a positive "no such record" must cross typed as 404 —
                    # not as a retryable 500, and under its OWN code so a
                    # route-level 404 (client/server skew, bad base_url)
                    # can never read as "instance confirmed gone"
                    self._error(404, CODE_INSTANCE_NOT_FOUND, str(e))
                except CloudAPIError as e:
                    self._error(500, CODE_INTERNAL, str(e))
                except Exception as e:  # a double must never hang the client
                    status, code = outer._classify_exception(e)
                    self._error(status, code, f"{e}")

            def do_GET(self):
                self._dispatch("GET")

            def do_POST(self):
                self._dispatch("POST")

            def do_PUT(self):
                self._dispatch("PUT")

            def do_DELETE(self):
                self._dispatch("DELETE")

        self._server = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="cloud-api-double", daemon=True
        )

    def _classify_exception(self, e: Exception):
        return 500, CODE_INTERNAL

    def _route(self, h, method: str) -> None:
        raise NotImplementedError

    # -- lifecycle ----------------------------------------------------------
    @property
    def url(self) -> str:
        host, port = self._server.server_address[:2]
        return f"http://{host}:{port}"

    def start(self):
        self._thread.start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


class CloudAPIServer(_JsonApiServer):
    """Serves one ``SimCloudAPI`` over localhost HTTP.

    Routes (all JSON):
      GET    /v1/instance-types?max-results=&next-token=   → paginated
      GET    /v1/subnets?tag:<k>=<v>…                      → {"items": [...]}
      GET    /v1/security-groups?tag:<k>=<v>…              → {"items": [...]}
      PUT    /v1/launch-templates/<name>   body=data       → {"name": ...}
      DELETE /v1/launch-templates/<name>
      POST   /v1/fleet     {"capacityType", "overrides"}   → instances + errors
      POST   /v1/instances/describe  {"ids": [...]}        → {"items": [...]}
      GET    /v1/instances                                 → full inventory with
                                                             launch tokens
      POST   /v1/instances/terminate {"ids": [...]}        → {}
      GET    /v1/events                                    → pending disruption
                                                             notices (drained)
      POST   /v1/events/requeue      body=notice           → re-offer a drained
                                                             notice (fleet routing)
    """

    def __init__(self, api: Optional[SimCloudAPI] = None, page_size: int = DEFAULT_PAGE_SIZE):
        self.api = api or SimCloudAPI()
        self.page_size = page_size
        self._fleet_results: Dict[str, Dict[str, Any]] = {}
        self._fleet_mu = threading.Lock()
        super().__init__()

    # -- routing ------------------------------------------------------------
    def _route(self, h, method: str) -> None:
        parsed = urllib.parse.urlsplit(h.path)
        path = parsed.path.rstrip("/")
        # keep blank values: "tag:Name=" is the key-exists wildcard selector
        query = urllib.parse.parse_qs(parsed.query, keep_blank_values=True)
        api = self.api

        if method == "GET" and path == "/v1/instance-types":
            items = [asdict(i) for i in api.describe_instance_types()]
            start = int(query.get("next-token", ["0"])[0])
            size = int(query.get("max-results", [str(self.page_size)])[0])
            page = items[start : start + size]
            body: Dict[str, Any] = {"items": page}
            if start + size < len(items):
                body["nextToken"] = str(start + size)
            h._send(200, body)
        elif method == "GET" and path == "/v1/subnets":
            selector = _tag_selector(query)
            h._send(200, {"items": [asdict(s) for s in api.describe_subnets(selector)]})
        elif method == "GET" and path == "/v1/security-groups":
            selector = _tag_selector(query)
            h._send(200, {"items": [asdict(g) for g in api.describe_security_groups(selector)]})
        elif method == "PUT" and path.startswith("/v1/launch-templates/"):
            name = urllib.parse.unquote(path.rsplit("/", 1)[1])
            out = api.ensure_launch_template(name, h._body())
            h._send(200, {"name": out})
        elif method == "DELETE" and path.startswith("/v1/launch-templates/"):
            name = urllib.parse.unquote(path.rsplit("/", 1)[1])
            api.delete_launch_template(name)
            h._send(200, {})
        elif method == "POST" and path == "/v1/fleet":
            body = h._body()
            if "capacityType" not in body:
                raise _BadRequest("fleet request missing capacityType")
            try:
                overrides = [
                    (o["launchTemplate"], o["instanceType"], o["zone"])
                    for o in body.get("overrides", [])
                ]
            except KeyError as e:
                raise _BadRequest(f"fleet override missing {e}") from e
            # idempotency: a retried POST (lost response / timeout) with the
            # same client token replays the recorded answer instead of
            # double-launching — the CreateFleet ClientToken contract. The
            # wire-level replay cache catches retries of THIS server; the
            # token also rides down to the control-plane double, whose own
            # ledger dedupes across server restarts and in-process callers.
            token = body.get("clientToken")
            if token is not None:
                with self._fleet_mu:
                    cached = self._fleet_results.get(token)
                if cached is not None:
                    # replay only while the recorded instances are still
                    # live: a delete between the first attempt and this
                    # retry must not resurrect a terminated instance as a
                    # fresh create result — drop the stale record and fall
                    # through (the control-plane ledger launches fresh)
                    ids = [i["id"] for i in cached.get("instances", [])]
                    live = {
                        i.id for i in api.describe_instances(ids)
                        if getattr(i, "state", "") != "terminated"
                    }
                    if all(i in live for i in ids):
                        h._send(200, cached)
                        return
                    with self._fleet_mu:
                        self._fleet_results.pop(token, None)
            instances, errors = api.create_fleet(
                body["capacityType"], overrides, client_token=token or ""
            )
            out = {
                "instances": [asdict(i) for i in instances],
                "errors": [
                    {"code": CODE_ICE, "capacityType": ct, "instanceType": it, "zone": z}
                    for ct, it, z in errors
                ],
            }
            if token is not None:
                with self._fleet_mu:
                    self._fleet_results[token] = out
                    while len(self._fleet_results) > 1024:
                        self._fleet_results.pop(next(iter(self._fleet_results)))
            h._send(200, out)
        elif method == "POST" and path == "/v1/instances/describe":
            ids = h._body().get("ids", [])
            h._send(200, {"items": [asdict(i) for i in api.describe_instances(ids)]})
        elif method == "GET" and path == "/v1/instances":
            # full inventory with launch tokens — the GC/recovery sweep
            h._send(200, {"items": [asdict(i) for i in api.list_instances()]})
        elif method == "POST" and path == "/v1/instances/terminate":
            api.terminate_instances(h._body().get("ids", []))
            h._send(200, {})
        elif method == "GET" and path == "/v1/events":
            # the disruption-event stream: GET drains pending notices (the
            # SQS receive-and-delete analog; the wire consumer is the only
            # reader, matching NoticeQueue's at-most-once contract)
            h._send(200, {"items": [n.to_wire() for n in api.poll_disruptions()]})
        elif method == "POST" and path == "/v1/events/requeue":
            # the re-offer endpoint (the SQS visibility-timeout analog): a
            # sharded replica that drained a notice for a node it does not
            # own hands it BACK so the owner's next poll picks it up
            api.send_disruption_notice(DisruptionNotice.from_wire(h._body()))
            h._send(200, {})
        else:
            h._error(404, CODE_NOT_FOUND, f"{method} {path}")


def _tag_selector(query: Dict[str, List[str]]) -> Dict[str, str]:
    return {
        k[len("tag:"):]: vs[0]
        for k, vs in query.items()
        if k.startswith("tag:")
    }


# ---------------------------------------------------------------------------
# client
# ---------------------------------------------------------------------------


class _WireTransport:
    """Shared HTTP transport with bounded retries under the resilience
    layer's policy (resilience/policy.py): up to ``max_attempts`` on 429
    (honoring Retry-After) and, for idempotent requests, on 5xx /
    connection errors with DECORRELATED-JITTER backoff from
    ``backoff_base``, all inside a hard per-operation ``deadline`` that the
    active reconcile-round Budget further caps. 4xx is deterministic and
    never retried; ``_typed_error`` maps the wire error code back to the
    vendor's exception vocabulary."""

    def __init__(
        self,
        base_url: str,
        timeout: float = 5.0,
        max_attempts: int = 4,
        backoff_base: float = 0.05,
        deadline: float = 30.0,
    ):
        self.base_url = base_url.rstrip("/")
        self.timeout = timeout
        self.max_attempts = max_attempts
        self.backoff_base = backoff_base
        self.deadline = deadline
        self.retries = 0  # observability: total retried requests

    def _typed_error(
        self, code: str, message: str, status: int, details: Optional[Dict] = None
    ) -> Exception:
        if code == CODE_ICE:
            overrides = [
                (o["capacityType"], o["instanceType"], o["zone"])
                for o in (details or {}).get("overrides", [])
            ]
            return InsufficientCapacityError(message, overrides=overrides)
        if code == CODE_INSTANCE_NOT_FOUND:
            # typed NotFound: the control plane positively answered "no such
            # record" — liveness consumers may treat it as confirmed-gone
            # without waiting out the consecutive-miss threshold. A
            # route-level CODE_NOT_FOUND stays a plain CloudAPIError.
            return InstanceNotFoundError(f"{code}: {message}")
        return CloudAPIError(f"{code or status}: {message}")

    def _request(
        self,
        method: str,
        path: str,
        body: Optional[Dict] = None,
        idempotent: bool = True,
    ) -> Dict:
        from karpenter_tpu import metrics
        from karpenter_tpu.resilience import current_budget, decorrelated_jitter

        url = self.base_url + path
        data = json.dumps(body).encode() if body is not None else None
        budget = current_budget.get()
        allowance = self.deadline
        if budget is not None:
            allowance = min(allowance, max(budget.remaining(), 0.0))
        start = time.monotonic()
        backoffs = decorrelated_jitter(self.backoff_base, cap=2.0)

        def pause(seconds: float) -> bool:
            """Sleep toward the next attempt — unless the deadline would
            pass first (the current error is final) or the wire's retry
            budget is dry (an overloaded far side must not receive
            amplified load — resilience/overload.py)."""
            from karpenter_tpu.resilience import default_retry_budget

            if time.monotonic() - start + seconds > allowance:
                metrics.RESILIENCE_DEADLINE_EXCEEDED.labels(dependency="wire").inc()
                return False
            if not default_retry_budget().try_spend("wire"):
                metrics.RESILIENCE_RETRIES.labels(
                    dependency="wire", outcome="budget_exhausted"
                ).inc()
                return False
            self.retries += 1
            metrics.RESILIENCE_RETRIES.labels(
                dependency="wire", outcome="retried"
            ).inc()
            time.sleep(seconds)
            return True

        from karpenter_tpu import obs

        span = obs.tracer().current()
        for attempt in range(self.max_attempts):
            final = attempt + 1 >= self.max_attempts
            req = urllib.request.Request(url, data=data, method=method)
            req.add_header("Content-Type", "application/json")
            if span is not None:
                # traceparent-style header: the far side opens a child span
                # under the caller's trace (see CloudAPIServer._dispatch)
                req.add_header("traceparent", obs.to_traceparent(span))
            try:
                with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                    out = json.loads(resp.read() or b"{}")
                from karpenter_tpu.resilience import default_retry_budget

                default_retry_budget().record_success("wire")
                return out
            except urllib.error.HTTPError as e:
                payload = {}
                try:
                    payload = json.loads(e.read() or b"{}")
                except Exception:
                    pass
                error = payload.get("error") or {}
                code = error.get("code", "")
                message = error.get("message", str(e))
                if e.code == 429 and not final:
                    # a throttle names its own pause; retried regardless of
                    # idempotency (the server rejected it unprocessed)
                    retry_after = float(e.headers.get("Retry-After") or self.backoff_base)
                    if pause(retry_after):
                        continue
                elif e.code >= 500 and not final and idempotent:
                    if pause(next(backoffs)):
                        continue
                raise self._typed_error(code, message, e.code, error.get("details"))
            except (urllib.error.URLError, ConnectionError, TimeoutError) as e:
                if final or not idempotent or not pause(next(backoffs)):
                    raise self._typed_error("", f"transport: {e}", 0) from e
        raise AssertionError("unreachable: every final attempt raises or returns")


class HttpCloudAPI(_WireTransport):
    """The providers' wire client: the ``SimCloudAPI`` method protocol over
    HTTP. 409 ``InsufficientInstanceCapacity`` and per-override fleet
    errors map back to the typed errors the providers classify; fleet
    launches carry a client token so transport-level retries of the
    non-idempotent POST cannot double-launch."""

    def __init__(self, base_url: str, page_size: Optional[int] = None, **kw):
        super().__init__(base_url, **kw)
        self.page_size = page_size

    # -- the SimCloudAPI protocol -------------------------------------------
    def describe_instance_types(self) -> List[SimInstanceTypeInfo]:
        items: List[Dict] = []
        token: Optional[str] = None
        while True:
            qs = []
            if self.page_size:
                qs.append(f"max-results={self.page_size}")
            if token is not None:
                qs.append(f"next-token={urllib.parse.quote(token)}")
            path = "/v1/instance-types" + ("?" + "&".join(qs) if qs else "")
            body = self._request("GET", path)
            items.extend(body.get("items", []))
            token = body.get("nextToken")
            if token is None:
                return [_from_dict(SimInstanceTypeInfo, d) for d in items]

    def describe_subnets(self, selector: Dict[str, str]) -> List[SimSubnet]:
        body = self._request("GET", "/v1/subnets" + _tag_query(selector))
        return [_from_dict(SimSubnet, d) for d in body.get("items", [])]

    def describe_security_groups(self, selector: Dict[str, str]) -> List[SimSecurityGroup]:
        body = self._request("GET", "/v1/security-groups" + _tag_query(selector))
        return [_from_dict(SimSecurityGroup, d) for d in body.get("items", [])]

    def ensure_launch_template(self, name: str, data: Dict[str, Any]) -> str:
        return self._request(
            "PUT", f"/v1/launch-templates/{urllib.parse.quote(name, safe='')}", data
        )["name"]

    def delete_launch_template(self, name: str) -> None:
        self._request(
            "DELETE", f"/v1/launch-templates/{urllib.parse.quote(name, safe='')}"
        )

    def create_fleet(
        self,
        capacity_type: str,
        overrides: Sequence[Tuple[str, str, str]],
        client_token: str = "",
    ) -> Tuple[List[SimInstance], List[Tuple[str, str, str]]]:
        import uuid

        body = self._request("POST", "/v1/fleet", {
            "capacityType": capacity_type,
            "overrides": [
                {"launchTemplate": lt, "instanceType": it, "zone": z}
                for lt, it, z in overrides
            ],
            # one token per LOGICAL launch: the caller's launch token when
            # it carries one (so PROVIDER-level retries of the whole create
            # also replay), else a per-call token — either way transport
            # retries replay the recorded result instead of launching a
            # second instance, which is what makes this POST idempotent for
            # the transport's 5xx retry policy
            "clientToken": client_token or uuid.uuid4().hex,
        }, idempotent=True)
        instances = [_from_dict(SimInstance, d) for d in body.get("instances", [])]
        errors = [
            (e["capacityType"], e["instanceType"], e["zone"])
            for e in body.get("errors", [])
            if e.get("code") == CODE_ICE
        ]
        return instances, errors

    def describe_instances(self, ids: List[str]) -> List[SimInstance]:
        body = self._request("POST", "/v1/instances/describe", {"ids": list(ids)})
        return [_from_dict(SimInstance, d) for d in body.get("items", [])]

    def list_instances(self) -> List[SimInstance]:
        body = self._request("GET", "/v1/instances")
        return [_from_dict(SimInstance, d) for d in body.get("items", [])]

    def terminate_instances(self, ids: List[str]) -> None:
        self._request("POST", "/v1/instances/terminate", {"ids": list(ids)})

    def poll_disruptions(self) -> List[DisruptionNotice]:
        body = self._request("GET", "/v1/events")
        return [DisruptionNotice.from_wire(d) for d in body.get("items", [])]

    def send_disruption_notice(self, notice: DisruptionNotice) -> None:
        """Re-offer a drained notice to the server's event bus (POST
        /v1/events/requeue) — the fleet-routing hook that lets a non-owner
        replica hand a foreign notice back across processes. Present on the
        wire client means ``SimulatedCloudProvider.requeue_disruption`` now
        answers True over HTTP, not only in-process."""
        self._request("POST", "/v1/events/requeue", notice.to_wire())


def _tag_query(selector: Dict[str, str]) -> str:
    if not selector:
        return ""
    return "?" + "&".join(
        f"tag:{urllib.parse.quote(k, safe='')}={urllib.parse.quote(v, safe='')}"
        for k, v in selector.items()
    )


def _from_dict(cls, d: Dict[str, Any]):
    """JSON dict → dataclass, tolerating tuple-typed fields serialized as
    lists (the wire has no tuples)."""
    import dataclasses

    kwargs = {}
    for f in dataclasses.fields(cls):
        if f.name not in d:
            continue
        v = d[f.name]
        if isinstance(v, list) and "Tuple" in str(f.type):
            v = tuple(v)
        kwargs[f.name] = v
    return cls(**kwargs)


# ---------------------------------------------------------------------------
# GKE node-pool surface over the same wire
# ---------------------------------------------------------------------------

CODE_STOCKOUT = "ZONAL_RESOURCE_POOL_EXHAUSTED"


class GkeAPIServer(_JsonApiServer):
    """Serves one ``SimGkeAPI`` over localhost HTTP:
      POST   /gke/v1/node-pools          {machineType, zone, spot, count,
                                          tpuTopology} → the pool (atomic;
                                          a stockout answers 409)
      DELETE /gke/v1/node-pools/<name>
      DELETE /gke/v1/instances/<name>
      GET    /gke/v1/events              → pending disruption notices (drained)
    """

    def __init__(self, api=None):
        from karpenter_tpu.cloudprovider.gke import SimGkeAPI

        self.api = api or SimGkeAPI()
        super().__init__()

    def _classify_exception(self, e: Exception):
        from karpenter_tpu.cloudprovider.gke import GkeApiError, GkeStockoutError

        if isinstance(e, GkeStockoutError):
            return 409, CODE_STOCKOUT
        if isinstance(e, GkeApiError):
            return 400, CODE_BAD_REQUEST
        return 500, CODE_INTERNAL

    def _route(self, h, method: str) -> None:
        from dataclasses import asdict as _asdict

        path = urllib.parse.urlsplit(h.path).path.rstrip("/")
        if method == "POST" and path == "/gke/v1/node-pools":
            b = h._body()
            pool = self.api.create_node_pool(
                b["machineType"], b["zone"], bool(b.get("spot")),
                int(b.get("count", 1)), b.get("tpuTopology", ""),
                launch_token=b.get("launchToken", ""),
            )
            h._send(200, _asdict(pool))
        elif method == "GET" and path == "/gke/v1/instances":
            # full inventory with launch tokens — the GC/recovery sweep
            h._send(
                200, {"items": [_asdict(i) for i in self.api.list_instances()]}
            )
        elif method == "POST" and path.endswith("/claim") and path.startswith(
            "/gke/v1/instances/"
        ):
            name = urllib.parse.unquote(path.rsplit("/", 2)[1])
            self.api.claim_instance(name, h._body().get("launchToken", ""))
            h._send(200, {})
        elif method == "DELETE" and path.startswith("/gke/v1/node-pools/"):
            self.api.delete_node_pool(urllib.parse.unquote(path.rsplit("/", 1)[1]))
            h._send(200, {})
        elif method == "DELETE" and path.startswith("/gke/v1/instances/"):
            self.api.delete_instance(urllib.parse.unquote(path.rsplit("/", 1)[1]))
            h._send(200, {})
        elif method == "GET" and path == "/gke/v1/events":
            h._send(
                200, {"items": [n.to_wire() for n in self.api.poll_disruptions()]}
            )
        elif method == "POST" and path == "/gke/v1/events/requeue":
            # the re-offer endpoint: foreign notices requeue across
            # processes so the shard owner's next poll sees them
            self.api.send_disruption_notice(DisruptionNotice.from_wire(h._body()))
            h._send(200, {})
        else:
            h._error(404, CODE_NOT_FOUND, f"{method} {path}")


class HttpGkeAPI(_WireTransport):
    """``SimGkeAPI``'s method protocol over HTTP — same transport/retry
    machinery as ``HttpCloudAPI`` (via the shared ``_WireTransport``; the
    EC2-style methods are deliberately NOT exposed here), with the GKE
    error vocabulary mapped back to ``GkeStockoutError`` / ``GkeApiError``."""

    def _typed_error(
        self, code: str, message: str, status: int, details: Optional[Dict] = None
    ) -> Exception:
        from karpenter_tpu.cloudprovider.gke import GkeApiError, GkeStockoutError

        if code == CODE_STOCKOUT or CODE_STOCKOUT in message:
            return GkeStockoutError(message)
        if status == 0 or status == 429 or status >= 500:
            # transport failures and exhausted 5xx/429 retries are TRANSIENT:
            # they must surface as a retryable error or the resilience
            # layer would classify a dead control plane as a healthy
            # deterministic answer and never trip its breaker
            return CloudAPIError(f"{code or status}: {message}")
        return GkeApiError(f"{code or status}: {message}")

    def create_node_pool(self, machine_type: str, zone: str, spot: bool,
                         count: int, tpu_topology: str = "",
                         launch_token: str = ""):
        from karpenter_tpu.cloudprovider.gke import GkeInstance, GkeNodePool

        # idempotent ONLY when tokened: with a launch token the server's
        # pool ledger replays a committed create, so transport retries are
        # safe; a token-less create keeps the conservative no-retry policy
        # (a replayed commit would orphan a possibly multi-host TPU pool)
        d = self._request("POST", "/gke/v1/node-pools", {
            "machineType": machine_type, "zone": zone, "spot": spot,
            "count": count, "tpuTopology": tpu_topology,
            "launchToken": launch_token,
        }, idempotent=bool(launch_token))
        instances = [_from_dict(GkeInstance, i) for i in d.pop("instances", [])]
        pool = _from_dict(GkeNodePool, d)
        pool.instances = instances
        return pool

    def claim_instance(self, name: str, launch_token: str) -> None:
        self._request(
            "POST",
            f"/gke/v1/instances/{urllib.parse.quote(name, safe='')}/claim",
            {"launchToken": launch_token},
        )

    def list_instances(self):
        from karpenter_tpu.cloudprovider.gke import GkeInstance

        body = self._request("GET", "/gke/v1/instances")
        return [_from_dict(GkeInstance, d) for d in body.get("items", [])]

    def delete_node_pool(self, name: str) -> None:
        self._request(
            "DELETE", f"/gke/v1/node-pools/{urllib.parse.quote(name, safe='')}"
        )

    def delete_instance(self, name: str) -> None:
        self._request(
            "DELETE", f"/gke/v1/instances/{urllib.parse.quote(name, safe='')}"
        )

    def poll_disruptions(self) -> List[DisruptionNotice]:
        body = self._request("GET", "/gke/v1/events")
        return [DisruptionNotice.from_wire(d) for d in body.get("items", [])]

    def send_disruption_notice(self, notice: DisruptionNotice) -> None:
        """Re-offer a drained notice (POST /gke/v1/events/requeue) — lets
        ``GkeCloudProvider.requeue_disruption`` answer True over the wire."""
        self._request("POST", "/gke/v1/events/requeue", notice.to_wire())
