from karpenter_tpu.cloudprovider.types import (  # noqa: F401
    CloudProvider,
    InstanceType,
    NodeRequest,
    Offering,
)
from karpenter_tpu.cloudprovider.requirements import (  # noqa: F401
    catalog_requirements,
    compatible,
    filter_instance_types,
)
