"""Catalog↔requirements glue (reference: pkg/cloudprovider/requirements.go)."""

from __future__ import annotations

from typing import Dict, List, Mapping, Sequence

from karpenter_tpu.api import labels as lbl
from karpenter_tpu.api.objects import NodeSelectorRequirement
from karpenter_tpu.api.requirements import Requirements
from karpenter_tpu.cloudprovider.types import InstanceType
from karpenter_tpu.utils import resources as res


# memo keyed by the catalog's object identities (the value holds the tuple
# so the ids stay valid): the union walks 400 types and runs on EVERY solve
# via the scheduler facade's idempotent re-layering. Identities are stable
# between catalog refreshes — providers TTL-cache the constructed
# InstanceType list (e.g. InstanceTypeProvider.get, 5 min). Concurrent
# per-provisioner workers share this, hence the lock.
import threading as _threading

_catreq_cache: Dict[tuple, tuple] = {}  # guarded-by: _catreq_lock
_catreq_lock = _threading.Lock()
_CATREQ_CACHE_MAX = 8


def catalog_requirements(instance_types: Sequence[InstanceType]) -> Requirements:
    """Union of supported {instance-type, zone, arch, os, capacity-type}
    values, layered into every provisioner at apply
    (reference: requirements.go:25-47). Requirements are immutable, so the
    identity-keyed memo hands out one shared object."""
    id_key = tuple(map(id, instance_types))
    with _catreq_lock:
        hit = _catreq_cache.get(id_key)
    if hit is not None:
        return hit[1]
    out = _catalog_requirements(instance_types)
    with _catreq_lock:
        while len(_catreq_cache) >= _CATREQ_CACHE_MAX:
            _catreq_cache.pop(next(iter(_catreq_cache)), None)
        _catreq_cache[id_key] = (tuple(instance_types), out)
    return out


def _catalog_requirements(instance_types: Sequence[InstanceType]) -> Requirements:
    supported: Dict[str, set] = {
        lbl.INSTANCE_TYPE: set(),
        lbl.TOPOLOGY_ZONE: set(),
        lbl.ARCH: set(),
        lbl.OS: set(),
        lbl.CAPACITY_TYPE: set(),
    }
    for it in instance_types:
        for offering in it.offerings:
            supported[lbl.TOPOLOGY_ZONE].add(offering.zone)
            supported[lbl.CAPACITY_TYPE].add(offering.capacity_type)
        supported[lbl.INSTANCE_TYPE].add(it.name)
        supported[lbl.ARCH].add(it.architecture)
        supported[lbl.OS].update(it.operating_systems)
    reqs = Requirements()
    for key, values in supported.items():
        reqs = reqs.add(NodeSelectorRequirement(key=key, operator="In", values=sorted(values)))
    return reqs


def compatible(it: InstanceType, requirements: Requirements) -> bool:
    """Per-key membership + at least one offering whose zone AND capacity
    type are both allowed (reference: requirements.go:49-66). Vendor-declared
    type labels (e.g. the GKE TPU topology) are checked like node labels: a
    requirement on a declared key must accept the type's value; requirements
    on keys the type does not declare stay non-excluding (they resolve at
    node level, like generated hostnames)."""
    if not requirements.get(lbl.INSTANCE_TYPE).has(it.name):
        return False
    if not requirements.get(lbl.ARCH).has(it.architecture):
        return False
    if not requirements.get(lbl.OS).has_any(it.operating_systems):
        return False
    for key, value in it.labels.items():
        if requirements.has(key) and not requirements.get(key).has(value):
            return False
    zone_set = requirements.get(lbl.TOPOLOGY_ZONE)
    ct_set = requirements.get(lbl.CAPACITY_TYPE)
    return any(zone_set.has(o.zone) and ct_set.has(o.capacity_type) for o in it.offerings)


def filter_instance_types(
    instance_types: Sequence[InstanceType],
    requirements: Requirements,
    requests: Mapping[str, float],
) -> List[InstanceType]:
    """Requirement-compatible types whose allocatable fits requests+overhead
    (reference: requirements.go:68-80)."""
    out: List[InstanceType] = []
    for it in instance_types:
        if not compatible(it, requirements):
            continue
        if not res.fits(res.merge(requests, it.overhead), it.resources):
            continue
        out.append(it)
    return out
