"""Simulated GKE provider: TPU podslice node pools.

A second vendor implementation beside the AWS-architecture simulated
provider (``simulated.py``): the machine-family catalog of a GKE cluster
with TPU v5e podslice node pools, so the framework schedules the workload
class it is itself built for — pods requesting ``google.com/tpu`` land on
``ct5lp-hightpu-*`` slices with the GKE TPU topology labels, flowing the
extended resource through the whole solve stack (encode extra axes,
signature frontiers, kernels, oracle).

Mirrors the vendor-layer shape the reference prescribes
(SURVEY §2.6: provider shell, instance-type provider, launch path,
defaulting/validation hooks); the cloud API is the in-process double, like
``SimCloudAPI``. GKE naming sources are the public machine families
(e2/n2/c3) and TPU podslice types (ct5lp-hightpu-{1,4,8}t; multi-host
slices appear as their per-host shapes with topology labels).
"""

from __future__ import annotations

import itertools
import threading
from typing import Any, Dict, List, Optional

from karpenter_tpu.api import labels as lbl
from karpenter_tpu.api.objects import Node, NodeSpec, NodeStatus, ObjectMeta, PodCondition
from karpenter_tpu.api.provisioner import Constraints
from karpenter_tpu.cloudprovider.types import CloudProvider, InstanceType, NodeRequest, Offering
from karpenter_tpu.utils import resources as res

TPU_RESOURCE = "google.com/tpu"
GKE_TPU_ACCELERATOR_LABEL = "cloud.google.com/gke-tpu-accelerator"
GKE_TPU_TOPOLOGY_LABEL = "cloud.google.com/gke-tpu-topology"

ZONES = ("us-central2-a", "us-central2-b", "us-central2-c")
CAPACITY_TYPES = ("on-demand", "spot")

_GIB = 1024 ** 3


# v5e podslice topology by chips-per-host — derived at label time so ANY
# catalog (custom, serde round-tripped) gets correct topology labels
TPU_TOPOLOGY_BY_CHIPS = {1: "1x1", 4: "2x2", 8: "2x4"}


def _machine(name: str, cpu: float, mem_gib: float, price: float,
             tpu_chips: int = 0) -> InstanceType:
    resources: Dict[str, float] = {
        res.CPU: cpu,
        res.MEMORY: mem_gib * _GIB,
        res.PODS: 110.0,
    }
    if tpu_chips:
        resources[TPU_RESOURCE] = float(tpu_chips)
    return InstanceType(
        name=name,
        offerings=[
            Offering(capacity_type=ct, zone=z)
            for ct, z in itertools.product(CAPACITY_TYPES, ZONES)
        ],
        architecture="amd64",
        operating_systems=frozenset({"linux"}),
        resources=resources,
        # GKE-style system reserve: flat kubelet/OS slice of the machine
        overhead={res.CPU: min(0.25, cpu * 0.06), res.MEMORY: 0.5 * _GIB},
        price=price,
    )


def gke_catalog() -> List[InstanceType]:
    """General-purpose machine families plus TPU v5e podslice hosts."""
    catalog: List[InstanceType] = []
    for family, per_cpu_mem, base in (("e2", 4, 0.031), ("n2", 4, 0.048), ("c3", 4, 0.056)):
        for cpus in (2, 4, 8, 16, 32, 48):
            catalog.append(
                _machine(
                    f"{family}-standard-{cpus}", cpus, cpus * per_cpu_mem,
                    price=round(base * cpus, 4),
                )
            )
    # TPU v5e podslice host shapes (topology derives from chip count)
    for name, cpus, mem, chips, price in (
        ("ct5lp-hightpu-1t", 24, 48, 1, 1.2),
        ("ct5lp-hightpu-4t", 112, 192, 4, 4.8),
        ("ct5lp-hightpu-8t", 224, 384, 8, 9.6),
    ):
        catalog.append(_machine(name, cpus, mem, price, tpu_chips=chips))
    return catalog


class GkeCloudProvider(CloudProvider):
    """In-process GKE double with the vendor hooks the webhook installs
    (reference vendor-layer shape: SURVEY §2.6)."""

    def __init__(self, catalog: Optional[List[InstanceType]] = None):
        self._catalog = catalog or gke_catalog()
        self._counter = itertools.count(1)
        self._lock = threading.Lock()
        self.create_calls: List[NodeRequest] = []
        self.delete_calls: List[str] = []

    # -- catalog -----------------------------------------------------------
    def get_instance_types(self, provider: Optional[Dict[str, Any]] = None) -> List[InstanceType]:
        return list(self._catalog)

    # -- launch ------------------------------------------------------------
    def create(self, request: NodeRequest) -> Node:
        with self._lock:
            self.create_calls.append(request)
            n = next(self._counter)
        if not request.instance_type_options:
            raise ValueError("no instance type options")
        it = request.instance_type_options[0]  # cheapest (solver sorts)
        reqs = request.template.requirements
        offering = next(
            (
                o
                for o in it.offerings
                if (not reqs.has(lbl.TOPOLOGY_ZONE) or reqs.get(lbl.TOPOLOGY_ZONE).has(o.zone))
                and (
                    not reqs.has(lbl.CAPACITY_TYPE)
                    or reqs.get(lbl.CAPACITY_TYPE).has(o.capacity_type)
                )
            ),
            None,
        )
        if offering is None:
            # launching a node whose labels contradict the certified
            # requirements would poison downstream controllers — fail loudly
            raise ValueError(
                f"no offering of {it.name} satisfies the request's "
                f"zone/capacity-type requirements"
            )
        labels = {
            lbl.INSTANCE_TYPE: it.name,
            lbl.TOPOLOGY_ZONE: offering.zone,
            lbl.CAPACITY_TYPE: offering.capacity_type,
            lbl.ARCH: it.architecture,
            lbl.OS: "linux",
        }
        chips = int(it.resources.get(TPU_RESOURCE, 0))
        if chips:
            labels[GKE_TPU_ACCELERATOR_LABEL] = "tpu-v5-lite-podslice"
            labels[GKE_TPU_TOPOLOGY_LABEL] = TPU_TOPOLOGY_BY_CHIPS.get(chips, f"1x{chips}")
        allocatable = {
            k: v - it.overhead.get(k, 0.0) for k, v in it.resources.items()
        }
        return Node(
            metadata=ObjectMeta(name=f"gke-node-{n}", namespace="", labels=labels),
            spec=NodeSpec(provider_id=f"gce://sim-project/{offering.zone}/gke-node-{n}"),
            status=NodeStatus(
                capacity=dict(it.resources),
                allocatable=allocatable,
                conditions=[PodCondition(type="Ready", status="True")],
            ),
        )

    def delete(self, node: Node) -> None:
        with self._lock:
            self.delete_calls.append(node.metadata.name)

    # -- webhook hooks -----------------------------------------------------
    def default(self, constraints: Constraints) -> None:
        """Default capacity type to on-demand (GKE: no spot unless asked),
        like the reference's vendor defaulting (provider_defaults.go:26-56)."""
        from karpenter_tpu.api.objects import NodeSelectorRequirement

        if not constraints.requirements.has(lbl.CAPACITY_TYPE):
            constraints.requirements = constraints.requirements.add(
                NodeSelectorRequirement(
                    key=lbl.CAPACITY_TYPE, operator="In", values=["on-demand"]
                )
            )

    def validate(self, constraints: Constraints) -> List[str]:
        errs: List[str] = []
        provider = constraints.provider or {}
        for key in provider:
            if key not in ("project", "network", "subnetwork", "serviceAccount", "tags"):
                errs.append(f"unknown GKE provider field {key!r}")
        return errs

    def name(self) -> str:
        return "gke"
