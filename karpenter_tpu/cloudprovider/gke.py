"""Simulated GKE provider: TPU podslice node pools.

A second vendor implementation beside the AWS-architecture simulated
provider (``simulated.py``), built to the same standard: a programmable
in-process cloud API double (``SimGkeAPI`` — node-pool create/delete with
stockout injection and error classification), an insufficient-capacity
cache that removes stocked-out offerings from the catalog for 45s
(reference: aws/instancetypes.go:41,185-198 and the create-path stockout
classification aws/instance.go:300-309), and **multi-host TPU podslices**:
one podslice = N nodes sharing ``cloud.google.com/gke-tpu-topology`` and a
node-pool name, launched atomically — the actual hard TPU provisioning
problem on GKE (VERDICT r2 missing #3).

Scheduling integration: pods requesting ``google.com/tpu`` with a
``gke-tpu-topology`` nodeSelector are routed to slice shapes through the
vendor-declared type labels (``InstanceType.labels`` participates in
requirement compatibility), flowing the extended resource and the topology
constraint through the whole solve stack (encode extra axes, signature
frontiers, kernels, oracle).

GKE naming sources are the public machine families (e2/n2/c3) and TPU
podslice machine types (ct5lp-hightpu-{1,4,8}t); multi-host slice shapes
are distinct catalog entries named ``<machine>-<topology>`` whose resources
are PER-HOST (each host contributes its chips), since the framework's
catalog is keyed by instance-type name.
"""

from __future__ import annotations

import itertools
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from karpenter_tpu.api import labels as lbl
from karpenter_tpu.api.objects import Node, NodeSpec, NodeStatus, ObjectMeta, PodCondition
from karpenter_tpu.api.provisioner import Constraints
from karpenter_tpu.cloudprovider.types import (
    CloudProvider,
    InstanceType,
    LiveInstance,
    NodeRequest,
    Offering,
)
from karpenter_tpu.interruption.types import DisruptionNotice, NoticeQueue
from karpenter_tpu.resilience.markers import idempotent
from karpenter_tpu.utils import resources as res
from karpenter_tpu.utils.ttlcache import TTLCache

TPU_RESOURCE = "google.com/tpu"
GKE_TPU_ACCELERATOR_LABEL = "cloud.google.com/gke-tpu-accelerator"
GKE_TPU_TOPOLOGY_LABEL = "cloud.google.com/gke-tpu-topology"
GKE_NODEPOOL_LABEL = "cloud.google.com/gke-nodepool"

ZONES = ("us-central2-a", "us-central2-b", "us-central2-c")
CAPACITY_TYPES = ("on-demand", "spot")

# stocked-out (type, zone, capacity-type) offerings sit out of the catalog
# for this long (reference: aws/instancetypes.go:41 — the ICE cache TTL)
UNAVAILABLE_OFFERINGS_TTL = 45.0

_GIB = 1024 ** 3


# v5e podslice topology by chips-per-host — single-host shapes
TPU_TOPOLOGY_BY_CHIPS = {1: "1x1", 4: "2x2", 8: "2x4"}

# multi-host podslice shapes: topology -> (hosts, chips per host). One
# podslice of topology "4x4" is 4 ct5lp-hightpu-4t hosts with 4 chips each.
MULTI_HOST_TOPOLOGIES = {
    "4x4": (4, 4),
    "4x8": (8, 4),
    "8x8": (16, 4),
}


class GkeStockoutError(Exception):
    """ZONAL_RESOURCE_POOL_EXHAUSTED / GCE_STOCKOUT — the offering has no
    capacity right now (classified like the reference classifies EC2's
    InsufficientInstanceCapacity, aws/instance.go:300-309)."""


class GkeApiError(Exception):
    """Any other node-pool API failure (quota, permission, malformed)."""


@dataclass
class GkeInstance:
    name: str
    machine_type: str
    zone: str
    spot: bool
    node_pool: str
    # the launch token of the create() call this host was handed to (the
    # GCE label analog): pool creation stamps the first host; pending
    # multi-host siblings are stamped as later creates claim them
    launch_token: str = ""
    created_at: float = 0.0


@dataclass
class GkeNodePool:
    name: str
    machine_type: str
    zone: str
    spot: bool
    count: int
    tpu_topology: str = ""
    instances: List[GkeInstance] = field(default_factory=list)


class SimGkeAPI:
    """Programmable in-process double of the GKE node-pool surface —
    ``SimCloudAPI``'s sibling. Tests inject stockouts per (machine type,
    zone[, capacity type]) and inspect recorded calls."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counter = itertools.count(1)
        self.node_pools: Dict[str, GkeNodePool] = {}
        self.create_calls: List[GkeNodePool] = []
        self.delete_calls: List[str] = []
        self._stockouts: set = set()
        # launch-token ledger: token -> pool name. A retried
        # create_node_pool with a committed token replays the recorded pool
        # instead of launching a second (possibly multi-host TPU) one.
        self._token_pools: Dict[str, str] = {}  # guarded-by: self._lock
        # the disruption-event bus: GCE preemption / maintenance notices
        # tests inject and the interruption controller polls
        self.disruptions = NoticeQueue()

    # -- fault injection ---------------------------------------------------
    def set_stockout(self, machine_type: str, zone: str, capacity_type: Optional[str] = None):
        """Future creates of this offering raise GkeStockoutError; a None
        capacity type stocks out both."""
        with self._lock:
            for ct in (capacity_type,) if capacity_type else CAPACITY_TYPES:
                self._stockouts.add((machine_type, zone, ct))

    def clear_stockout(self, machine_type: str, zone: str, capacity_type: Optional[str] = None):
        with self._lock:
            for ct in (capacity_type,) if capacity_type else CAPACITY_TYPES:
                self._stockouts.discard((machine_type, zone, ct))

    # -- API surface -------------------------------------------------------
    def create_node_pool(
        self,
        machine_type: str,
        zone: str,
        spot: bool,
        count: int,
        tpu_topology: str = "",
        launch_token: str = "",
    ) -> GkeNodePool:
        """Create a node pool of ``count`` instances ATOMICALLY: a stockout
        yields zero instances, never a partial podslice (a partial slice is
        useless to a multi-host workload). A ``launch_token`` the control
        plane already committed replays the recorded pool — a transport
        retry after a lost response cannot launch a second slice."""
        if count < 1:
            raise GkeApiError(f"node pool count must be >= 1, got {count}")
        ct = "spot" if spot else "on-demand"
        with self._lock:
            if launch_token:
                committed = self._token_pools.get(launch_token)
                if committed is not None and committed in self.node_pools:
                    return self.node_pools[committed]
            if (machine_type, zone, ct) in self._stockouts:
                raise GkeStockoutError(
                    f"ZONAL_RESOURCE_POOL_EXHAUSTED: {machine_type} in {zone} ({ct})"
                )
            n = next(self._counter)
            now = time.time()
            pool = GkeNodePool(
                name=f"np-{machine_type}-{n}",
                machine_type=machine_type,
                zone=zone,
                spot=spot,
                count=count,
                tpu_topology=tpu_topology,
            )
            pool.instances = [
                GkeInstance(
                    name=f"gke-{pool.name}-{i}",
                    machine_type=machine_type,
                    zone=zone,
                    spot=spot,
                    node_pool=pool.name,
                    # the creating call is handed host 0; pending siblings
                    # stay token-less until claim_instance stamps them
                    launch_token=launch_token if i == 0 else "",
                    created_at=now,
                )
                for i in range(count)
            ]
            self.node_pools[pool.name] = pool
            self.create_calls.append(pool)
            if launch_token:
                self._token_pools[launch_token] = pool.name
            return pool

    def claim_instance(self, name: str, launch_token: str) -> None:
        """Stamp the claiming create's token onto a pending multi-host
        sibling — each host of a slice carries the token of the create()
        that returned it, so crash recovery can re-find ANY host by its
        journal entry's token."""
        with self._lock:
            for pool in self.node_pools.values():
                for inst in pool.instances:
                    if inst.name == name:
                        inst.launch_token = launch_token
                        return

    def list_instances(self) -> List[GkeInstance]:
        """Full inventory across pools — the GC/recovery sweep surface."""
        with self._lock:
            return [
                inst
                for pool in self.node_pools.values()
                for inst in pool.instances
            ]

    def delete_node_pool(self, name: str) -> None:
        with self._lock:
            self.delete_calls.append(name)
            self.node_pools.pop(name, None)

    def delete_instance(self, name: str) -> None:
        """Remove one instance; an emptied pool is reaped."""
        with self._lock:
            self.delete_calls.append(name)
            for pool_name, pool in list(self.node_pools.items()):
                pool.instances = [i for i in pool.instances if i.name != name]
                if not pool.instances:
                    self.node_pools.pop(pool_name, None)

    def send_disruption_notice(self, notice: DisruptionNotice) -> None:
        """Fault injector: announce a preemption/maintenance event for one
        instance (node names equal instance names here)."""
        self.disruptions.push(notice)

    def poll_disruptions(self) -> List[DisruptionNotice]:
        return self.disruptions.drain()


def _machine(name: str, cpu: float, mem_gib: float, price: float,
             tpu_chips: int = 0, tpu_topology: str = "") -> InstanceType:
    resources: Dict[str, float] = {
        res.CPU: cpu,
        res.MEMORY: mem_gib * _GIB,
        res.PODS: 110.0,
    }
    labels: Dict[str, str] = {}
    if tpu_chips:
        resources[TPU_RESOURCE] = float(tpu_chips)
        labels[GKE_TPU_ACCELERATOR_LABEL] = "tpu-v5-lite-podslice"
        labels[GKE_TPU_TOPOLOGY_LABEL] = (
            tpu_topology or TPU_TOPOLOGY_BY_CHIPS.get(tpu_chips, f"1x{tpu_chips}")
        )
    return InstanceType(
        name=name,
        offerings=[
            Offering(capacity_type=ct, zone=z)
            for ct, z in itertools.product(CAPACITY_TYPES, ZONES)
        ],
        architecture="amd64",
        operating_systems=frozenset({"linux"}),
        resources=resources,
        # GKE-style system reserve: flat kubelet/OS slice of the machine
        overhead={res.CPU: min(0.25, cpu * 0.06), res.MEMORY: 0.5 * _GIB},
        price=price,
        labels=labels,
    )


def gke_catalog() -> List[InstanceType]:
    """General-purpose machine families, single-host TPU v5e podslice
    shapes, and multi-host podslice shapes (per-host resources; the
    provider launches ``hosts`` nodes atomically)."""
    catalog: List[InstanceType] = []
    for family, per_cpu_mem, base in (("e2", 4, 0.031), ("n2", 4, 0.048), ("c3", 4, 0.056)):
        for cpus in (2, 4, 8, 16, 32, 48):
            catalog.append(
                _machine(
                    f"{family}-standard-{cpus}", cpus, cpus * per_cpu_mem,
                    price=round(base * cpus, 4),
                )
            )
    # TPU v5e podslice host shapes (topology derives from chip count)
    for name, cpus, mem, chips, price in (
        ("ct5lp-hightpu-1t", 24, 48, 1, 1.2),
        ("ct5lp-hightpu-4t", 112, 192, 4, 4.8),
        ("ct5lp-hightpu-8t", 224, 384, 8, 9.6),
    ):
        catalog.append(_machine(name, cpus, mem, price, tpu_chips=chips))
    # multi-host podslices: one catalog entry per slice topology; the price
    # is per HOST (the whole slice costs hosts x price)
    for topology, (hosts, chips) in MULTI_HOST_TOPOLOGIES.items():
        catalog.append(
            _machine(
                f"ct5lp-hightpu-4t-{topology}", 112, 192, 4.8,
                tpu_chips=chips, tpu_topology=topology,
            )
        )
    return catalog


def slice_hosts(instance_type_name: str) -> int:
    """How many hosts one podslice of this type spans (1 = single-host)."""
    for topology, (hosts, _) in MULTI_HOST_TOPOLOGIES.items():
        if instance_type_name.endswith(f"-{topology}"):
            return hosts
    return 1


class GkeCloudProvider(CloudProvider):
    """GKE vendor layer against ``SimGkeAPI``: offering selection with ICE
    fallback, atomic multi-host slice launches, node materialization with
    the GKE TPU labels, and the webhook defaulting/validation hooks."""

    def __init__(
        self,
        catalog: Optional[List[InstanceType]] = None,
        api: Optional[SimGkeAPI] = None,
        clock=None,
    ):
        self._catalog = catalog or gke_catalog()
        self.api = api or SimGkeAPI()
        self._lock = threading.Lock()
        self.create_calls: List[NodeRequest] = []
        self.delete_calls: List[str] = []
        # stocked-out offerings sit out of the catalog for 45s
        self._unavailable = TTLCache(UNAVAILABLE_OFFERINGS_TTL, clock=clock)
        # multi-host slices already launched whose remaining hosts are
        # waiting to be claimed by subsequent create() calls
        self._pending_hosts: Dict[Tuple[str, str, str], List[Node]] = {}
        # launch-token replay: token -> the node this provider's create
        # already returned for it. Covers the pending-host claim path the
        # API-level pool ledger cannot see (a claim consumes no API call).
        self._token_nodes: Dict[str, Node] = {}  # guarded-by: self._lock

    # -- catalog -----------------------------------------------------------
    @idempotent
    def get_instance_types(self, provider: Optional[Dict[str, Any]] = None) -> List[InstanceType]:
        """The catalog minus offerings in the unavailable (ICE) cache —
        reference: aws/instancetypes.go:185-198."""
        out: List[InstanceType] = []
        for it in self._catalog:
            offerings = [
                o for o in it.offerings
                if self._unavailable.get((it.name, o.zone, o.capacity_type)) is None
            ]
            if not offerings:
                continue
            if len(offerings) == len(it.offerings):
                out.append(it)
            else:
                out.append(
                    InstanceType(
                        name=it.name,
                        offerings=offerings,
                        architecture=it.architecture,
                        operating_systems=it.operating_systems,
                        resources=dict(it.resources),
                        overhead=dict(it.overhead),
                        price=it.price,
                        labels=dict(it.labels),
                    )
                )
        return out

    # -- launch ------------------------------------------------------------
    @idempotent
    def create(self, request: NodeRequest) -> Node:
        # idempotent BY TOKEN: a token this provider (or the node-pool API)
        # already committed returns the SAME node — a retried create after
        # a timed-out first attempt yields exactly one host, never two
        token = request.launch_token
        with self._lock:
            self.create_calls.append(request)
            if token and token in self._token_nodes:
                return self._token_nodes[token]
        if not request.instance_type_options:
            raise ValueError("no instance type options")
        reqs = request.template.requirements
        last_err: Optional[Exception] = None
        ice_skipped = False
        # options are price-sorted by the solver; within a type, try each
        # allowed offering, falling through stockouts to the next zone and
        # then to the next (pricier) type — the reference's ICE fallback
        for it in request.instance_type_options:
            hosts = slice_hosts(it.name)
            for o in it.offerings:
                if reqs.has(lbl.TOPOLOGY_ZONE) and not reqs.get(lbl.TOPOLOGY_ZONE).has(o.zone):
                    continue
                if reqs.has(lbl.CAPACITY_TYPE) and not reqs.get(lbl.CAPACITY_TYPE).has(o.capacity_type):
                    continue
                key = (it.name, o.zone, o.capacity_type)
                if self._unavailable.get(key) is not None:
                    ice_skipped = True
                    continue
                # one critical section from pending-check through pool
                # creation to the pending store: provision_once launches
                # vnodes from a thread pool, and two concurrent creates of
                # the same slice key must not both create a pool (duplicate
                # pools + the second store would orphan the first's
                # unclaimed hosts, breaking the atomic-slice invariant)
                with self._lock:
                    pending = self._pending_hosts.get(key)
                    if pending:
                        node = pending.pop(0)
                        if not pending:
                            del self._pending_hosts[key]
                        self._stamp_token_locked(node, token)
                        return node
                    try:
                        pool = self.api.create_node_pool(
                            machine_type=it.name,
                            zone=o.zone,
                            spot=o.capacity_type == "spot",
                            count=hosts,
                            tpu_topology=it.labels.get(GKE_TPU_TOPOLOGY_LABEL, ""),
                            launch_token=token,
                        )
                    except GkeStockoutError as e:
                        # classified capacity error: cache the offering out
                        # for the ICE TTL, fall through to the next offering
                        self._unavailable.set(key, True)
                        last_err = e
                        continue
                    nodes = [self._node(it, o, inst) for inst in pool.instances]
                    first = nodes.pop(0)
                    if nodes:
                        self._pending_hosts[key] = nodes
                    self._stamp_token_locked(first, token, claim=False)
                    return first
        if last_err is not None:
            raise last_err
        if ice_skipped:
            # every candidate offering is sitting out its ICE TTL — this is
            # a (transient) capacity condition, not a requirements bug
            raise GkeStockoutError(
                "all candidate offerings are capacity-constrained (ICE-cached)"
            )
        raise ValueError(
            "no offering satisfies the request's zone/capacity-type requirements"
        )

    def _stamp_token_locked(self, node: Node, token: str, claim: bool = True) -> None:
        """Pair ``node`` with the claiming create's token: annotation on the
        Node, entry in the replay cache, and (for a pending-host claim) the
        tag on the cloud instance itself so ``list_instances`` reports it.
        Caller holds ``self._lock``."""
        if not token:
            return
        node.metadata.annotations[lbl.LAUNCH_TOKEN_ANNOTATION] = token
        self._token_nodes[token] = node
        while len(self._token_nodes) > 4096:  # bound the long-lived ledger
            self._token_nodes.pop(next(iter(self._token_nodes)))
        if claim:
            claimer = getattr(self.api, "claim_instance", None)
            if claimer is not None:
                claimer(node.metadata.name, token)

    def list_instances(self) -> List[LiveInstance]:
        """Live inventory for the GC/adoption cross-check. Hosts still
        PENDING (launched as part of a slice, not yet claimed by a create)
        carry no token — the GC grace period is what protects them while
        their siblings' creates are in flight."""
        lister = getattr(self.api, "list_instances", None)
        if lister is None:
            return NotImplemented
        out: List[LiveInstance] = []
        for inst in lister():
            out.append(
                LiveInstance(
                    id=inst.name,
                    launch_token=inst.launch_token,
                    instance_type=inst.machine_type,
                    zone=inst.zone,
                    capacity_type="spot" if inst.spot else "on-demand",
                    created_at=inst.created_at,
                    provider_id=f"gce://sim-project/{inst.zone}/{inst.name}",
                    labels={GKE_NODEPOOL_LABEL: inst.node_pool},
                )
            )
        return out

    def _node(self, it: InstanceType, offering: Offering, inst: GkeInstance) -> Node:
        labels = {
            lbl.INSTANCE_TYPE: it.name,
            lbl.TOPOLOGY_ZONE: offering.zone,
            lbl.CAPACITY_TYPE: offering.capacity_type,
            lbl.ARCH: it.architecture,
            lbl.OS: "linux",
            GKE_NODEPOOL_LABEL: inst.node_pool,
        }
        labels.update(it.labels)  # accelerator + topology for TPU shapes
        # derived at label time so ANY catalog (custom, serde round-tripped,
        # labels-free) still yields correctly-labeled TPU nodes
        chips = int(it.resources.get(TPU_RESOURCE, 0))
        if chips:
            labels.setdefault(GKE_TPU_ACCELERATOR_LABEL, "tpu-v5-lite-podslice")
            labels.setdefault(
                GKE_TPU_TOPOLOGY_LABEL,
                TPU_TOPOLOGY_BY_CHIPS.get(chips, f"1x{chips}"),
            )
        allocatable = {k: v - it.overhead.get(k, 0.0) for k, v in it.resources.items()}
        return Node(
            metadata=ObjectMeta(name=inst.name, namespace="", labels=labels),
            spec=NodeSpec(
                provider_id=f"gce://sim-project/{offering.zone}/{inst.name}"
            ),
            status=NodeStatus(
                capacity=dict(it.resources),
                allocatable=allocatable,
                conditions=[PodCondition(type="Ready", status="True")],
            ),
        )

    @idempotent
    def delete(self, node: Node) -> None:
        pool = node.metadata.labels.get(GKE_NODEPOOL_LABEL)
        purged: List[Node] = []
        with self._lock:
            self.delete_calls.append(node.metadata.name)
            # a deleted node's token must not replay a dead instance
            token = node.metadata.annotations.get(lbl.LAUNCH_TOKEN_ANNOTATION)
            if token:
                self._token_nodes.pop(token, None)
            if pool:
                # a multi-host slice is dying: its unclaimed pending hosts
                # must die with it — handing a stale sibling out later would
                # pair a "fresh" node with hosts scaled down long ago
                for key, nodes in list(self._pending_hosts.items()):
                    keep = [
                        n for n in nodes
                        if n.metadata.labels.get(GKE_NODEPOOL_LABEL) != pool
                    ]
                    if len(keep) != len(nodes):
                        purged += [n for n in nodes if n not in keep]
                        if keep:
                            self._pending_hosts[key] = keep
                        else:
                            del self._pending_hosts[key]
        self.api.delete_instance(node.metadata.name)
        for n in purged:
            self.api.delete_instance(n.metadata.name)

    # -- webhook hooks -----------------------------------------------------
    def default(self, constraints: Constraints) -> None:
        """Default capacity type to on-demand (GKE: no spot unless asked),
        like the reference's vendor defaulting (provider_defaults.go:26-56)."""
        from karpenter_tpu.api.objects import NodeSelectorRequirement

        if not constraints.requirements.has(lbl.CAPACITY_TYPE):
            constraints.requirements = constraints.requirements.add(
                NodeSelectorRequirement(
                    key=lbl.CAPACITY_TYPE, operator="In", values=["on-demand"]
                )
            )

    def validate(self, constraints: Constraints) -> List[str]:
        errs: List[str] = []
        provider = constraints.provider or {}
        for key in provider:
            if key not in ("project", "network", "subnetwork", "serviceAccount", "tags"):
                errs.append(f"unknown GKE provider field {key!r}")
        return errs

    @idempotent
    def poll_disruptions(self) -> List[DisruptionNotice]:
        """DisruptionSource: drain the node-pool API's event bus (the same
        call works over the wire via ``HttpGkeAPI``)."""
        return self.api.poll_disruptions()

    def requeue_disruption(self, notice: DisruptionNotice) -> bool:
        """Fleet routing: re-offer a wrong-replica notice to the event bus
        (in-process via the double's injector, over the wire via POST
        /gke/v1/events/requeue)."""
        sender = getattr(self.api, "send_disruption_notice", None)
        if sender is None:
            return False
        sender(notice)
        return True

    def name(self) -> str:
        return "gke"
