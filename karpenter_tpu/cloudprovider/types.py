"""Cloud-provider abstraction.

Mirrors ``pkg/cloudprovider/types.go``: ``CloudProvider`` {create, delete,
get_instance_types, default, validate, name}, the ``InstanceType`` catalog
record {name, offerings, architecture, operating_systems, resources, overhead,
price}, and ``NodeRequest`` {template (constraints), instance-type options}.
"""

from __future__ import annotations

import abc
from dataclasses import dataclass, field
from typing import TYPE_CHECKING, Any, Dict, FrozenSet, List, Optional, Sequence

if TYPE_CHECKING:  # interruption.types imports nothing from this layer
    from karpenter_tpu.interruption.types import DisruptionNotice

from karpenter_tpu.api.objects import Node
from karpenter_tpu.api.provisioner import Constraints
from karpenter_tpu.utils import resources as res


@dataclass(frozen=True)
class Offering:
    """A purchasable (capacity type, zone) combination
    (reference: types.go:76-81)."""

    capacity_type: str
    zone: str


@dataclass
class InstanceType:
    """One catalog entry (reference: types.go:60-74). ``resources`` is the
    node's allocatable; ``overhead`` the kubelet/system reserve subtracted
    from it before pods fit; ``price`` the optimization weight."""

    name: str
    offerings: List[Offering] = field(default_factory=list)
    architecture: str = "amd64"
    operating_systems: FrozenSet[str] = frozenset({"linux"})
    resources: Dict[str, float] = field(default_factory=dict)
    overhead: Dict[str, float] = field(default_factory=dict)
    price: Optional[float] = None
    # vendor-declared node labels that participate in requirement
    # compatibility (e.g. GKE's cloud.google.com/gke-tpu-topology): a
    # requirement on a declared key must accept the type's value
    labels: Dict[str, str] = field(default_factory=dict)

    def effective_price(self) -> float:
        """Explicit price, else the cpu+mem+gpu formula the fake catalog uses
        (reference: fake/instancetype.go:146-163)."""
        if self.price is not None and self.price != 0:
            return self.price
        price = 0.0
        price += 0.1 * self.resources.get(res.CPU, 0.0)
        price += 0.1 * self.resources.get(res.MEMORY, 0.0) / 1e9
        if self.resources.get(res.NVIDIA_GPU, 0.0) or self.resources.get(res.AMD_GPU, 0.0):
            price += 1.0
        return price

    def zones(self) -> FrozenSet[str]:
        return frozenset(o.zone for o in self.offerings)

    def capacity_types(self) -> FrozenSet[str]:
        return frozenset(o.capacity_type for o in self.offerings)


@dataclass
class NodeRequest:
    """What the provisioner asks the cloud for (reference: types.go:53-56).

    ``launch_token`` is the client-side idempotency token (the CreateFleet
    ClientToken contract, aws/instance.go:120): the provider stamps it on
    the launched instance as a label/tag, and a second ``create`` carrying
    the SAME token returns the SAME instance instead of launching twice —
    which is what lets the retry policy cover ``create`` and lets crash
    recovery (launch/journal.py) re-find an instance whose launching
    process died before the Node object was written."""

    template: Constraints
    instance_type_options: Sequence[InstanceType] = ()
    launch_token: str = ""


@dataclass
class LiveInstance:
    """One live machine as the cloud control plane reports it — the
    ``list_instances`` record the launch journal's recovery and the
    garbage-collection controller cross-check against Node objects.
    ``launch_token`` is the client token the launching ``create`` stamped
    (empty for instances launched out-of-band or by pre-token builds);
    ``created_at`` is provider-clock seconds (``time.time`` domain) so the
    GC grace period can spare instances still mid-registration."""

    id: str
    launch_token: str = ""
    instance_type: str = ""
    zone: str = ""
    capacity_type: str = ""
    created_at: float = 0.0
    provider_id: str = ""
    labels: Dict[str, str] = field(default_factory=dict)


class CloudProvider(abc.ABC):
    """Vendor interface (reference: types.go:34-51)."""

    @abc.abstractmethod
    def create(self, request: NodeRequest) -> Node:
        """Launch a node satisfying the request; returns the created node
        (with instance-type/zone/capacity-type labels and allocatable set)."""

    @abc.abstractmethod
    def delete(self, node: Node) -> None:
        """Terminate the backing instance."""

    @abc.abstractmethod
    def get_instance_types(self, provider: Optional[Dict[str, Any]] = None) -> List[InstanceType]:
        """The current catalog for a vendor provider config."""

    def default(self, constraints: Constraints) -> None:
        """Vendor defaulting hook (webhook DefaultHook)."""

    def validate(self, constraints: Constraints) -> List[str]:
        """Vendor validation hook (webhook ValidateHook)."""
        return []

    def poll_disruptions(self) -> List["DisruptionNotice"]:
        """The ``DisruptionSource`` protocol (karpenter_tpu/interruption):
        return-and-clear the notices that arrived since the last poll.
        Default: this vendor has no disruption stream."""
        return []

    def requeue_disruption(self, notice) -> bool:
        """Hand a drained disruption notice BACK to the stream — the fleet
        routing hook: a sharded controller replica that polls a notice for
        a node whose shard it does not own re-offers it so the owner's poll
        picks it up (real queues get this via visibility timeouts; doubles
        push back onto their in-memory queue). Returns False when this
        vendor cannot requeue — the caller then handles the notice locally
        (availability over strict sharding)."""
        return False

    def list_instances(self):
        """Inventory for the crash-consistency cross-check: every live
        instance this vendor is running, as :class:`LiveInstance` records
        carrying the launch token stamped at create. The launch journal's
        recovery re-describes unresolved tokens against this list, and the
        garbage-collection controller compares it against Node objects to
        adopt journaled orphans and terminate unjournaled leaks. Returns
        ``NotImplemented`` when this vendor has no list surface (the GC
        controller then opts the provider out of orphan sweeps)."""
        return NotImplemented

    def instance_gone(self, node: Node):
        """Liveness probe for the instance backing ``node``: True when the
        cloud has confirmed it is gone (terminated state, a typed NotFound,
        or enough consecutive describe misses to rule out a flaky
        response), False when it is alive, None when the probe itself
        failed this time (unknown — the consumer keeps its cadence), and
        ``NotImplemented`` when this vendor has no describe surface at all
        (the consumer opts the node out of liveness probing). One missing
        id in one flaky describe must NOT answer True — see
        resilience.MissTracker."""
        return NotImplemented

    def name(self) -> str:
        return type(self).__name__.lower()
