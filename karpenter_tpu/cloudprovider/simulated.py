"""Simulated vendor cloud provider — the deep vendor layer.

The reference's deepest layer is the AWS provider (``pkg/cloudprovider/aws``,
~2,400 LoC): catalog discovery with TTL caches, tag-selector subnet/security-
group discovery, launch-template resolution, a fleet-style launch path with
insufficient-capacity (ICE) caching, and an overhead model. This module
rebuilds that architecture against ``SimCloudAPI`` — a programmable cloud
control-plane double with capacity pools and error injection (the analog of
``aws/fake/ec2api.go``) — so the full vendor code path is exercised without
an AWS account, exactly how the reference's own suite drives the real
provider code through fake APIs (aws/suite_test.go).

Component map (reference file → here):
- aws/cloudprovider.go:53-188   → SimulatedCloudProvider
- aws/instance.go:72-368        → InstanceProvider
- aws/instancetypes.go:40-198   → InstanceTypeProvider + UnavailableOfferings
- aws/instancetype.go:119-238   → SimInstanceType (resources + overhead model)
- aws/launchtemplate.go:74-274  → LaunchTemplateProvider
- aws/subnets.go, securitygroups.go → SubnetProvider / SecurityGroupProvider
- aws/apis/v1alpha1/provider*.go → SimProviderConfig (+defaults/validation)
- aws/fake/ec2api.go            → SimCloudAPI
"""

from __future__ import annotations

import hashlib
import itertools
import json
import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

from karpenter_tpu.api import labels as lbl
from karpenter_tpu.api.objects import (
    Node,
    NodeSelectorRequirement,
    NodeSpec,
    NodeStatus,
    ObjectMeta,
)
from karpenter_tpu.api.provisioner import Constraints
from karpenter_tpu.cloudprovider.types import (
    CloudProvider,
    InstanceType,
    LiveInstance,
    NodeRequest,
    Offering,
)
from karpenter_tpu.interruption.types import DisruptionNotice, NoticeQueue
from karpenter_tpu.resilience.markers import idempotent
from karpenter_tpu.utils import resources as res
from karpenter_tpu.utils.ttlcache import TTLCache
from karpenter_tpu.utils.workqueue import TokenBucket

logger = logging.getLogger("karpenter.simulated")

# reference: aws/cloudprovider.go:47-57
CACHE_TTL = 60.0
INSTANCE_TYPES_TTL = 300.0
UNAVAILABLE_OFFERINGS_TTL = 45.0  # reference: aws/instancetypes.go:41
MAX_INSTANCE_TYPES = 20  # reference: aws/cloudprovider.go:57

# fleet-call budget (reference: aws/instance.go:43-49)
CREATE_FLEET_QPS = 2.0
CREATE_FLEET_BURST = 100

# DescribeInstances is eventually consistent after a fleet launch
# (reference: aws/instance.go:84-91 retries 6x)
DESCRIBE_RETRIES = 6

# Consecutive describe responses an instance id must be missing from before
# the node-liveness consumer may declare it gone — describe_instances drops
# unknown ids silently, so one chaotic response must not orphan a node
LIVENESS_MISS_THRESHOLD = 3

DEFAULT_IMAGE_FAMILY = "standard"
DEFAULT_SELECTOR = {"purpose": "nodes"}
IMAGE_FAMILIES = ("standard", "minimal", "gpu")


class InsufficientCapacityError(Exception):
    """The fleet request could not be satisfied for any override.

    ``overrides`` carries the (capacity_type, instance_type, zone) triples
    that errored, so the caller's ICE cache can mark exactly the exhausted
    pools — an all-ICE fleet answer is a typed capacity condition, not an
    empty result indistinguishable from an empty-override bug."""

    def __init__(self, message: str, overrides: Sequence[Tuple[str, str, str]] = ()):
        super().__init__(message)
        self.overrides = list(overrides)


class CloudAPIError(Exception):
    """Injected control-plane failure."""


class InstanceNotFoundError(CloudAPIError):
    """Typed NotFound: the control plane positively confirmed it has no
    record of the instance (as opposed to dropping the id from one flaky
    describe response)."""


# ---------------------------------------------------------------------------
# The programmable control-plane double (reference: aws/fake/ec2api.go)
# ---------------------------------------------------------------------------


@dataclass
class SimInstanceTypeInfo:
    """Raw catalog record as the cloud API reports it
    (the ec2.InstanceTypeInfo analog)."""

    name: str
    vcpus: float
    memory_gib: float
    architecture: str = lbl.ARCH_AMD64
    gpus: float = 0.0
    gpu_vendor: str = ""  # "" | "nvidia" | "amd"
    max_network_interfaces: int = 4
    ips_per_interface: int = 15
    zones: Tuple[str, ...] = ("sim-zone-1a", "sim-zone-1b", "sim-zone-1c")
    capacity_types: Tuple[str, ...] = (lbl.CAPACITY_TYPE_SPOT, lbl.CAPACITY_TYPE_ON_DEMAND)
    bare_metal: bool = False
    price_per_hour: Optional[float] = None


@dataclass
class SimSubnet:
    id: str
    zone: str
    tags: Dict[str, str] = field(default_factory=dict)


@dataclass
class SimSecurityGroup:
    id: str
    tags: Dict[str, str] = field(default_factory=dict)


@dataclass
class SimInstance:
    id: str
    instance_type: str
    zone: str
    capacity_type: str
    launch_template: str
    state: str = "running"
    # the client launch token stamped at create_fleet (the EC2 tag analog):
    # what makes a retried fleet call replay instead of double-launching,
    # and what crash recovery re-describes unresolved journal entries by
    launch_token: str = ""
    created_at: float = 0.0


def default_sim_catalog() -> List[SimInstanceTypeInfo]:
    """A realistic small catalog: general-purpose ladder + GPU + ARM + metal."""
    out: List[SimInstanceTypeInfo] = []
    for i, (vcpus, mem) in enumerate([(1, 2), (2, 4), (4, 8), (8, 16), (16, 32), (32, 64), (64, 128)]):
        out.append(SimInstanceTypeInfo(name=f"sim.gp-{vcpus}x", vcpus=vcpus, memory_gib=mem))
    out.append(SimInstanceTypeInfo(name="sim.gpu-8x", vcpus=8, memory_gib=64, gpus=1, gpu_vendor="nvidia"))
    out.append(SimInstanceTypeInfo(name="sim.gpu-32x", vcpus=32, memory_gib=256, gpus=4, gpu_vendor="nvidia"))
    out.append(SimInstanceTypeInfo(name="sim.arm-16x", vcpus=16, memory_gib=32, architecture=lbl.ARCH_ARM64))
    out.append(SimInstanceTypeInfo(name="sim.metal-96x", vcpus=96, memory_gib=384, bare_metal=True))
    return out


class SimCloudAPI:
    """Behavior-programmable cloud control plane: capacity pools simulate
    insufficient capacity per (capacityType, instanceType, zone); methods can
    be made to fail via ``inject_error`` (reference: aws/fake/ec2api.go:35-137)."""

    def __init__(
        self,
        catalog: Optional[List[SimInstanceTypeInfo]] = None,
        subnets: Optional[List[SimSubnet]] = None,
        security_groups: Optional[List[SimSecurityGroup]] = None,
    ):
        self.catalog = catalog if catalog is not None else default_sim_catalog()
        self.subnets = subnets if subnets is not None else [
            SimSubnet("subnet-1", "sim-zone-1a", {"purpose": "nodes", "Name": "private-a"}),
            SimSubnet("subnet-2", "sim-zone-1b", {"purpose": "nodes", "Name": "private-b"}),
            SimSubnet("subnet-3", "sim-zone-1c", {"purpose": "nodes", "Name": "private-c"}),
        ]
        self.security_groups = security_groups if security_groups is not None else [
            SimSecurityGroup("sg-nodes", {"purpose": "nodes"}),
            SimSecurityGroup("sg-extra", {"purpose": "extra"}),
        ]
        # pools with no capacity: set of (capacity_type, instance_type, zone)
        self.insufficient_capacity_pools: Set[Tuple[str, str, str]] = set()
        self.launch_templates: Dict[str, Dict[str, Any]] = {}
        self.instances: Dict[str, SimInstance] = {}
        self.calls: Dict[str, int] = {}
        # the disruption-event bus (the EventBridge/SQS analog): tests push
        # notices via send_disruption_notice; the interruption controller
        # drains them through the provider's poll_disruptions
        self.disruptions = NoticeQueue()
        self._errors: Dict[str, List[Exception]] = {}
        self._counter = itertools.count(1)
        self._mu = threading.Lock()
        # client-token ledger: token -> instance id. A retried create_fleet
        # with a token the control plane has already committed replays the
        # recorded instance instead of launching a second one — the
        # CreateFleet ClientToken contract, now honored by the in-process
        # double itself (not only the HTTP wire's replay cache), so every
        # caller gets idempotent creates.
        self._fleet_tokens: Dict[str, str] = {}  # guarded-by: self._mu
        # simulated provisioning latency: create_fleet sleeps this long
        # OUTSIDE the mutex (parallel launches overlap, like the real
        # control plane) — what makes a cold launch measurably slower than
        # a warm-pool claim in the bench storm legs
        self.launch_latency_s: float = 0.0

    # -- error injection ----------------------------------------------------
    def inject_error(self, method: str, error: Exception) -> None:
        self._errors.setdefault(method, []).append(error)

    def _enter(self, method: str) -> None:
        with self._mu:
            self.calls[method] = self.calls.get(method, 0) + 1
            pending = self._errors.get(method)
            if pending:
                raise pending.pop(0)

    # -- control-plane methods ----------------------------------------------
    def describe_instance_types(self) -> List[SimInstanceTypeInfo]:
        self._enter("describe_instance_types")
        return list(self.catalog)

    def describe_subnets(self, selector: Dict[str, str]) -> List[SimSubnet]:
        self._enter("describe_subnets")
        return [s for s in self.subnets if _tags_match(s.tags, selector)]

    def describe_security_groups(self, selector: Dict[str, str]) -> List[SimSecurityGroup]:
        self._enter("describe_security_groups")
        return [g for g in self.security_groups if _tags_match(g.tags, selector)]

    def ensure_launch_template(self, name: str, data: Dict[str, Any]) -> str:
        self._enter("ensure_launch_template")
        with self._mu:
            self.launch_templates.setdefault(name, data)
        return name

    def delete_launch_template(self, name: str) -> None:
        self._enter("delete_launch_template")
        with self._mu:
            self.launch_templates.pop(name, None)

    def create_fleet(
        self,
        capacity_type: str,
        overrides: Sequence[Tuple[str, str, str]],  # (launch_template, instance_type, zone)
        client_token: str = "",
    ) -> Tuple[List[SimInstance], List[Tuple[str, str, str]]]:
        """Launch ONE instance from the first override whose capacity pool is
        healthy; returns (instances, ICE-errored overrides) — the
        CreateFleet(type=instant, TotalTargetCapacity=1) analog
        (reference: aws/instance.go:120-156, fake/ec2api.go:78-137).
        A ``client_token`` the control plane has already committed replays
        the recorded instance: same token, same instance, never a second
        launch."""
        self._enter("create_fleet")
        if self.launch_latency_s > 0:
            time.sleep(self.launch_latency_s)
        errors: List[Tuple[str, str, str]] = []
        with self._mu:
            if client_token:
                committed = self._fleet_tokens.get(client_token)
                if (
                    committed is not None
                    and committed in self.instances
                    and self.instances[committed].state != "terminated"
                ):
                    return [self.instances[committed]], errors
            for lt, itype, zone in overrides:
                if (capacity_type, itype, zone) in self.insufficient_capacity_pools:
                    errors.append((capacity_type, itype, zone))
                    continue
                inst = SimInstance(
                    id=f"i-{next(self._counter):08x}",
                    instance_type=itype,
                    zone=zone,
                    capacity_type=capacity_type,
                    launch_template=lt,
                    launch_token=client_token,
                    created_at=time.time(),
                )
                self.instances[inst.id] = inst
                if client_token:
                    self._fleet_tokens[client_token] = inst.id
                return [inst], errors
        if errors:
            # EVERY override hit an exhausted pool: surface it typed (with
            # the pools) instead of an empty result a caller could mistake
            # for an empty-override bug
            raise InsufficientCapacityError(
                f"all {len(errors)} overrides insufficient", overrides=errors
            )
        return [], errors

    def describe_instances(self, ids: List[str]) -> List[SimInstance]:
        self._enter("describe_instances")
        with self._mu:
            return [self.instances[i] for i in ids if i in self.instances]

    def list_instances(self) -> List[SimInstance]:
        """Full inventory (the DescribeInstances-no-filter analog) — what
        the launch journal's recovery and the GC controller sweep."""
        self._enter("list_instances")
        with self._mu:
            return list(self.instances.values())

    def terminate_instances(self, ids: List[str]) -> None:
        self._enter("terminate_instances")
        with self._mu:
            for i in ids:
                inst = self.instances.get(i)
                if inst:
                    inst.state = "terminated"
                    # release the token ledger entry (Fake/GKE pop theirs
                    # on delete): a token replay must never resurrect a
                    # terminated instance as a live create result
                    if inst.launch_token:
                        self._fleet_tokens.pop(inst.launch_token, None)

    def send_disruption_notice(self, notice: DisruptionNotice) -> None:
        """Fault injector: put a disruption notice on the event bus. Node
        names are instance ids here (``_to_node`` names Node objects after
        the instance), so callers pass the instance id."""
        self.disruptions.push(notice)

    def poll_disruptions(self) -> List[DisruptionNotice]:
        self._enter("poll_disruptions")
        return self.disruptions.drain()


def _tags_match(tags: Dict[str, str], selector: Dict[str, str]) -> bool:
    """Tag selector semantics: ``""`` value = wildcard (key exists)
    (reference: aws/subnets.go:46-87)."""
    for k, v in selector.items():
        if v == "" or v == "*":
            if k not in tags:
                return False
        elif tags.get(k) != v:
            return False
    return True


# ---------------------------------------------------------------------------
# Vendor provider config (reference: aws/apis/v1alpha1/provider*.go)
# ---------------------------------------------------------------------------


@dataclass
class BlockDeviceMapping:
    """reference: aws/apis/v1alpha1/provider.go BlockDeviceMappings."""

    device_name: str = "/dev/xvda"
    volume_size_gib: int = 20
    volume_type: str = "gp3"
    encrypted: bool = True
    delete_on_termination: bool = True

    def validate(self) -> List[str]:
        errs = []
        if not self.device_name:
            errs.append("blockDeviceMapping deviceName must not be empty")
        if self.volume_size_gib <= 0:
            errs.append(f"blockDeviceMapping volumeSize {self.volume_size_gib} must be positive")
        if self.volume_type not in ("gp2", "gp3", "io1", "io2", "st1", "sc1", "standard"):
            errs.append(f"blockDeviceMapping volumeType {self.volume_type} not recognized")
        return errs


@dataclass
class MetadataOptions:
    """Instance metadata service settings
    (reference: aws/apis/v1alpha1/provider.go MetadataOptions)."""

    http_endpoint: str = "enabled"
    http_tokens: str = "required"  # IMDSv2 by default
    http_put_response_hop_limit: int = 2

    def validate(self) -> List[str]:
        errs = []
        if self.http_endpoint not in ("enabled", "disabled"):
            errs.append(f"metadataOptions httpEndpoint {self.http_endpoint} invalid")
        if self.http_tokens not in ("required", "optional"):
            errs.append(f"metadataOptions httpTokens {self.http_tokens} invalid")
        if not 1 <= self.http_put_response_hop_limit <= 64:
            errs.append(
                f"metadataOptions hopLimit {self.http_put_response_hop_limit} not in 1..64"
            )
        return errs


@dataclass
class SimProviderConfig:
    """The vendor block embedded in ``provisioner.spec.provider``."""

    instance_profile: str = ""
    subnet_selector: Dict[str, str] = field(default_factory=lambda: dict(DEFAULT_SELECTOR))
    security_group_selector: Dict[str, str] = field(default_factory=lambda: dict(DEFAULT_SELECTOR))
    image_family: str = DEFAULT_IMAGE_FAMILY
    tags: Dict[str, str] = field(default_factory=dict)
    launch_template: str = ""  # bring-your-own template name
    block_device_mappings: List[BlockDeviceMapping] = field(default_factory=list)
    metadata_options: MetadataOptions = field(default_factory=MetadataOptions)
    # presence flags: explicitly-specified fields conflict with launchTemplate
    # even when they equal the defaults
    security_group_selector_specified: bool = False
    metadata_options_specified: bool = False
    # malformed input collected at deserialize time so validate() can report
    # field errors instead of the parse crashing reconcile/webhook paths
    parse_errors: List[str] = field(default_factory=list)

    @staticmethod
    def deserialize(provider: Optional[Dict[str, Any]]) -> "SimProviderConfig":
        """reference: aws/apis/v1alpha1/provider.go:195-210. Lenient: bad
        field types become ``parse_errors`` surfaced by ``validate()``."""
        if not provider:
            return SimProviderConfig()
        errors: List[str] = []

        def build(cls, raw, key_map: Dict[str, str], label: str):
            """Dataclass from present keys only — the dataclass defaults stay
            the single source of truth for absent fields."""
            kwargs = {}
            if raw is None:
                raw = {}
            if not isinstance(raw, dict):
                errors.append(f"{label} must be an object, got {type(raw).__name__}")
                raw = {}
            for doc_key, field_name in key_map.items():
                if doc_key not in raw:
                    continue
                value = raw[doc_key]
                target = cls.__dataclass_fields__[field_name].type
                try:
                    if target == "int":
                        value = int(value)
                    elif target == "bool":
                        if isinstance(value, str):
                            value = value.lower() == "true"
                        else:
                            value = bool(value)
                    else:
                        value = str(value)
                except (TypeError, ValueError):
                    errors.append(f"{label}.{doc_key}: invalid value {value!r}")
                    continue
                kwargs[field_name] = value
            return cls(**kwargs)

        bdms_raw = provider.get("blockDeviceMappings") or []
        if not isinstance(bdms_raw, list):
            errors.append("blockDeviceMappings must be a list")
            bdms_raw = []
        bdms = [
            build(
                BlockDeviceMapping,
                b,
                {
                    "deviceName": "device_name",
                    "volumeSize": "volume_size_gib",
                    "volumeType": "volume_type",
                    "encrypted": "encrypted",
                    "deleteOnTermination": "delete_on_termination",
                },
                f"blockDeviceMappings[{i}]",
            )
            for i, b in enumerate(bdms_raw)
        ]
        metadata = build(
            MetadataOptions,
            provider.get("metadataOptions"),
            {
                "httpEndpoint": "http_endpoint",
                "httpTokens": "http_tokens",
                "httpPutResponseHopLimit": "http_put_response_hop_limit",
            },
            "metadataOptions",
        )
        return SimProviderConfig(
            instance_profile=str(provider.get("instanceProfile", "")),
            # absent → default; explicitly empty/null → {} so validate rejects
            subnet_selector=dict(provider.get("subnetSelector", DEFAULT_SELECTOR) or {}),
            security_group_selector=dict(
                provider.get("securityGroupSelector", DEFAULT_SELECTOR) or {}
            ),
            image_family=str(provider.get("imageFamily", DEFAULT_IMAGE_FAMILY)),
            tags=dict(provider.get("tags") or {}),
            launch_template=str(provider.get("launchTemplate", "")),
            block_device_mappings=bdms,
            metadata_options=metadata,
            security_group_selector_specified="securityGroupSelector" in provider,
            metadata_options_specified="metadataOptions" in provider,
            parse_errors=errors,
        )

    def validate(self) -> List[str]:
        """reference: aws/apis/v1alpha1/provider_validation.go:41-226."""
        errs = list(self.parse_errors)
        if self.image_family not in IMAGE_FAMILIES:
            errs.append(f"imageFamily {self.image_family} not in {IMAGE_FAMILIES}")
        if self.launch_template and (
            self.security_group_selector_specified
            or self.security_group_selector != DEFAULT_SELECTOR
        ):
            # a custom launch template brings its own security groups
            errs.append("may not specify both launchTemplate and securityGroupSelector")
        if self.launch_template and self.block_device_mappings:
            errs.append("may not specify both launchTemplate and blockDeviceMappings")
        if self.launch_template and self.metadata_options_specified:
            # BYO templates carry their own IMDS settings; silently dropping
            # the user's would be worse than rejecting
            errs.append("may not specify both launchTemplate and metadataOptions")
        for selector, name in ((self.subnet_selector, "subnetSelector"),
                               (self.security_group_selector, "securityGroupSelector")):
            if not selector:
                errs.append(f"{name} must not be empty")
        for k in self.tags:
            if k.startswith(lbl.GROUP):
                errs.append(f"tag {k} uses the restricted {lbl.GROUP} prefix")
        for bdm in self.block_device_mappings:
            errs.extend(bdm.validate())
        errs.extend(self.metadata_options.validate())
        return errs


# ---------------------------------------------------------------------------
# Instance types + overhead model (reference: aws/instancetype.go)
# ---------------------------------------------------------------------------


def network_limited_pods(info: SimInstanceTypeInfo) -> float:
    """max interfaces × (ips per interface − 1) + 2
    (reference: aws/instancetype.go:236-241)."""
    return float(info.max_network_interfaces * (info.ips_per_interface - 1) + 2)


def compute_overhead(info: SimInstanceTypeInfo) -> Dict[str, float]:
    """Kubelet/system reserve: 100m system CPU + a kube-reserved CPU
    percentage ladder, memory ``11·pods + 255 + 100 + 100`` MiB
    (reference: aws/instancetype.go:190-234)."""
    cpu_milli = info.vcpus * 1000.0
    cpu_overhead = 100.0  # system-reserved
    for start, end, pct in ((0, 1000, 0.06), (1000, 2000, 0.01),
                            (2000, 4000, 0.005), (4000, 1 << 31, 0.0025)):
        if cpu_milli >= start:
            span = min(cpu_milli, end) - start
            cpu_overhead += span * pct
    mem_mib = 11 * network_limited_pods(info) + 255 + 100 + 100
    return {
        res.CPU: cpu_overhead / 1000.0,
        res.MEMORY: mem_mib * 1024**2,
    }


def to_instance_type(
    info: SimInstanceTypeInfo,
    zones: Set[str],
    unavailable: "UnavailableOfferings",
) -> InstanceType:
    """Catalog record → scheduler-facing InstanceType: offerings are the
    (viable zones ∩ subnet zones) × capacity-type cross product minus
    ICE-cached pools (reference: aws/instancetypes.go:66-114)."""
    offerings = [
        Offering(ct, z)
        for ct in info.capacity_types
        for z in sorted(zones & set(info.zones))
        if not unavailable.is_unavailable(ct, info.name, z)
    ]
    resources = {
        res.CPU: info.vcpus,
        res.MEMORY: info.memory_gib * 1024**3,
        res.PODS: network_limited_pods(info),
        res.EPHEMERAL_STORAGE: 20 * 1024**3,
    }
    if info.gpus:
        resources[res.NVIDIA_GPU if info.gpu_vendor == "nvidia" else res.AMD_GPU] = info.gpus
    price = info.price_per_hour
    if price is None:
        price = 0.04 * info.vcpus + 0.005 * info.memory_gib + 0.9 * info.gpus
    return InstanceType(
        name=info.name,
        offerings=offerings,
        architecture=info.architecture,
        operating_systems=frozenset({lbl.OS_LINUX}),
        resources=resources,
        overhead=compute_overhead(info),
        price=price,
    )


class UnavailableOfferings:
    """ICE cache: offerings that returned insufficient capacity are skipped
    for 45s (reference: aws/instancetypes.go:185-198)."""

    def __init__(self, clock=None):
        self.cache = TTLCache(UNAVAILABLE_OFFERINGS_TTL, clock=clock)

    def mark_unavailable(self, capacity_type: str, instance_type: str, zone: str) -> None:
        logger.info("offering %s:%s:%s unavailable for %ss",
                    capacity_type, instance_type, zone, UNAVAILABLE_OFFERINGS_TTL)
        self.cache.set(f"{capacity_type}:{instance_type}:{zone}", True)

    def is_unavailable(self, capacity_type: str, instance_type: str, zone: str) -> bool:
        return self.cache.get(f"{capacity_type}:{instance_type}:{zone}") is not None


class InstanceTypeProvider:
    """Catalog discovery with a 5-minute TTL cache
    (reference: aws/instancetypes.go:40-114)."""

    def __init__(self, api: SimCloudAPI, subnet_provider: "SubnetProvider", clock=None):
        self.api = api
        self.subnet_provider = subnet_provider
        self.unavailable = UnavailableOfferings(clock=clock)
        self._cache = TTLCache(INSTANCE_TYPES_TTL, clock=clock)

    def get(self, config: SimProviderConfig) -> List[InstanceType]:
        zones = {s.zone for s in self.subnet_provider.get(config)}
        # the raw catalog is selector-independent; the zone intersection is
        # applied per call below, so one cache entry serves every selector
        infos = self._cache.get_or_compute("types", self.api.describe_instance_types)
        out = []
        for info in infos:
            if info.bare_metal:  # opinionated filter (reference: instancetypes.go:167)
                continue
            it = to_instance_type(info, zones, self.unavailable)
            if it.offerings:
                out.append(it)
        return out

    def invalidate(self) -> None:
        self._cache.clear()


class SubnetProvider:
    """Tag-selector subnet discovery, cached (reference: aws/subnets.go:46-87)."""

    def __init__(self, api: SimCloudAPI, clock=None):
        self.api = api
        self._cache = TTLCache(CACHE_TTL, clock=clock)

    def get(self, config: SimProviderConfig) -> List[SimSubnet]:
        key = tuple(sorted(config.subnet_selector.items()))
        subnets = self._cache.get_or_compute(
            key, lambda: self.api.describe_subnets(config.subnet_selector)
        )
        if not subnets:
            raise CloudAPIError(f"no subnets matched selector {config.subnet_selector}")
        return subnets


class SecurityGroupProvider:
    """reference: aws/securitygroups.go:45-99."""

    def __init__(self, api: SimCloudAPI, clock=None):
        self.api = api
        self._cache = TTLCache(CACHE_TTL, clock=clock)

    def get(self, config: SimProviderConfig) -> List[SimSecurityGroup]:
        key = tuple(sorted(config.security_group_selector.items()))
        groups = self._cache.get_or_compute(
            key, lambda: self.api.describe_security_groups(config.security_group_selector)
        )
        if not groups:
            raise CloudAPIError(
                f"no security groups matched selector {config.security_group_selector}"
            )
        return groups


class LaunchTemplateProvider:
    """Resolve (image family × constraints) to an ensured launch template;
    the template name is a stable hash of its parameters so identical
    configurations share one template (reference: aws/launchtemplate.go:74-186
    and the amifamily strategy pattern, amifamily/resolver.go:69-110)."""

    def __init__(self, api: SimCloudAPI, security_groups: SecurityGroupProvider):
        self.api = api
        self.security_groups = security_groups
        self._cache: Dict[str, str] = {}
        self._mu = threading.Lock()

    def get(self, config: SimProviderConfig, constraints: Constraints, needs_gpu: bool) -> str:
        if config.launch_template:
            return config.launch_template  # bring-your-own
        image = self._resolve_image(config.image_family, needs_gpu)
        groups = [g.id for g in self.security_groups.get(config)]
        bdms = config.block_device_mappings or [BlockDeviceMapping()]
        data = {
            "image": image,
            "instance_profile": config.instance_profile,
            "security_groups": sorted(groups),
            "tags": dict(sorted(config.tags.items())),
            "labels": dict(_sorted_labels(constraints)),
            "taints": _rendered_taints(constraints),
            "block_device_mappings": [
                {
                    "device_name": b.device_name,
                    "volume_size_gib": b.volume_size_gib,
                    "volume_type": b.volume_type,
                    "encrypted": b.encrypted,
                    "delete_on_termination": b.delete_on_termination,
                }
                for b in bdms
            ],
            "metadata_options": {
                "http_endpoint": config.metadata_options.http_endpoint,
                "http_tokens": config.metadata_options.http_tokens,
                "http_put_response_hop_limit": config.metadata_options.http_put_response_hop_limit,
            },
            "user_data": bootstrap_user_data(config.image_family, constraints),
        }
        name = "karpenter-lt-" + hashlib.sha256(
            json.dumps(data, sort_keys=True).encode()
        ).hexdigest()[:16]
        with self._mu:
            if name not in self._cache:
                self.api.ensure_launch_template(name, data)
                self._cache[name] = name
        return name

    @staticmethod
    def _resolve_image(family: str, needs_gpu: bool) -> str:
        """The AMI-family strategy: GPU nodes get the accelerated image
        variant (reference: amifamily/al2.go:31-60 picks GPU AMIs)."""
        if needs_gpu:
            return f"img-{family}-gpu-v1"
        return f"img-{family}-v1"


def _sorted_labels(constraints: Constraints):
    return sorted(constraints.labels.items())


def _rendered_taints(constraints: Constraints) -> List[str]:
    """One rendering shared by the template hash and the bootstrap payload —
    they must never disagree."""
    return sorted(f"{t.key}={t.value}:{t.effect}" for t in constraints.taints)


def bootstrap_user_data(image_family: str, constraints: Constraints) -> str:
    """Node bootstrap payload: kubelet register-time labels/taints and
    cluster DNS, per image family — the reference's bootstrap-script
    generator shapes the same arguments (amifamily/bootstrap/
    eksbootstrap.go:32; Bottlerocket uses TOML instead of shell).
    ``standard``/``gpu`` render a shell bootstrap; ``minimal`` renders a
    TOML settings file (the Bottlerocket analog)."""
    labels = ",".join(f"{k}={v}" for k, v in _sorted_labels(constraints))
    taints = ",".join(_rendered_taints(constraints))
    dns = ""
    if constraints.kubelet_configuration and constraints.kubelet_configuration.cluster_dns:
        dns = constraints.kubelet_configuration.cluster_dns[0]
    if image_family == "minimal":
        lines = ["[settings.kubernetes]"]
        if labels:
            lines.append(f'node-labels = "{labels}"')
        if taints:
            lines.append(f'node-taints = "{taints}"')
        if dns:
            lines.append(f'cluster-dns-ip = "{dns}"')
        return "\n".join(lines)
    args = ["/etc/bootstrap.sh"]
    if labels:
        args.append(f"--node-labels={labels}")
    if taints:
        args.append(f"--register-with-taints={taints}")
    if dns:
        args.append(f"--cluster-dns={dns}")
    return " ".join(args)


class InstanceProvider:
    """The launch path (reference: aws/instance.go:72-368)."""

    def __init__(
        self,
        api: SimCloudAPI,
        instance_types: InstanceTypeProvider,
        subnets: SubnetProvider,
        launch_templates: LaunchTemplateProvider,
    ):
        self.api = api
        self.instance_types = instance_types
        self.subnets = subnets
        self.launch_templates = launch_templates
        # client-side flow control on the fleet call
        # (reference: aws/instance.go:43-49, 2 QPS / 100 burst)
        self.fleet_limiter = TokenBucket(CREATE_FLEET_QPS, CREATE_FLEET_BURST)

    def create(self, config: SimProviderConfig, request: NodeRequest,
               token: str = "") -> Node:
        # GPU filter BEFORE the 20-type cap: a GPU-heavy prefix must not
        # starve out the generic types (reference: aws/instance.go:73-75)
        options = self._prefer_generic(list(request.instance_type_options))
        options = options[:MAX_INSTANCE_TYPES]
        if not options:
            raise InsufficientCapacityError("no instance type options")
        capacity_type = self._get_capacity_type(request.template, options)
        needs_gpu = any(
            it.resources.get(res.NVIDIA_GPU, 0) or it.resources.get(res.AMD_GPU, 0)
            for it in options
        )
        lt = self.launch_templates.get(config, request.template, needs_gpu)
        zones = request.template.requirements.zones()
        subnet_zones = {s.zone for s in self.subnets.get(config)}
        overrides = [
            (lt, it.name, o.zone)
            for it in options
            for o in it.offerings
            if o.capacity_type == capacity_type
            and o.zone in subnet_zones
            and (not zones or o.zone in zones)
        ]
        if not overrides:
            raise InsufficientCapacityError(
                f"no launchable offering for capacity type {capacity_type}"
            )
        if not self.fleet_limiter.take(timeout=60):
            raise CloudAPIError("fleet request rate budget exhausted (2 QPS/100 burst)")
        try:
            # the launch token rides the fleet call: a committed token
            # replays the same instance (in-process ledger or the wire's
            # replay cache), so a retried create cannot double-launch
            instances, errors = self.api.create_fleet(
                capacity_type, overrides, client_token=token
            )
        except InsufficientCapacityError as e:
            # the typed all-ICE answer (in-process raise, or the wire's 409
            # with details): cache out exactly the pools the control plane
            # reported exhausted, then let the capacity error propagate
            for ct, itype, zone in e.overrides:
                self.instance_types.unavailable.mark_unavailable(ct, itype, zone)
            raise
        for ct, itype, zone in errors:
            self.instance_types.unavailable.mark_unavailable(ct, itype, zone)
        if not instances:
            raise InsufficientCapacityError(
                f"fleet returned no instances ({len(errors)} unavailable pools)",
                overrides=errors,
            )
        instance = self._describe_with_retry(instances[0].id)
        return self._to_node(instance, options)

    def _describe_with_retry(self, instance_id: str) -> SimInstance:
        """DescribeInstances right after a launch is eventually consistent
        (reference: aws/instance.go:84-91, 6 retries)."""
        last_err: Optional[Exception] = None
        for attempt in range(DESCRIBE_RETRIES):
            try:
                found = self.api.describe_instances([instance_id])
                if found:
                    return found[0]
            except CloudAPIError as e:
                last_err = e
            if attempt < DESCRIBE_RETRIES - 1:  # no dead sleep before raising
                time.sleep(min(0.05 * (2**attempt), 1.0))
        raise CloudAPIError(
            f"instance {instance_id} not visible after {DESCRIBE_RETRIES} retries"
        ) from last_err

    def delete(self, node: Node) -> None:
        instance_id = node.spec.provider_id.rsplit("/", 1)[-1]
        self.api.terminate_instances([instance_id])

    @staticmethod
    def _get_capacity_type(template: Constraints, options: Sequence[InstanceType]) -> str:
        """Spot iff requested AND offered; default on-demand
        (reference: aws/instance.go:311-323)."""
        if lbl.CAPACITY_TYPE_SPOT in template.requirements.capacity_types():
            zones = template.requirements.zones()
            for it in options:
                for o in it.offerings:
                    if o.capacity_type == lbl.CAPACITY_TYPE_SPOT and (not zones or o.zone in zones):
                        return lbl.CAPACITY_TYPE_SPOT
        return lbl.CAPACITY_TYPE_ON_DEMAND

    @staticmethod
    def _prefer_generic(options: List[InstanceType]) -> List[InstanceType]:
        """Drop GPU types when a generic type suffices
        (reference: aws/instance.go:327-345)."""
        generic = [
            it
            for it in options
            if not it.resources.get(res.NVIDIA_GPU, 0) and not it.resources.get(res.AMD_GPU, 0)
        ]
        return generic if generic else options

    @staticmethod
    def _to_node(instance: SimInstance, options: Sequence[InstanceType]) -> Node:
        it = next(o for o in options if o.name == instance.instance_type)
        allocatable = {
            k: max(v - it.overhead.get(k, 0.0), 0.0) for k, v in it.resources.items()
        }
        return Node(
            metadata=ObjectMeta(
                name=instance.id,
                namespace="",
                labels={
                    lbl.INSTANCE_TYPE: instance.instance_type,
                    lbl.TOPOLOGY_ZONE: instance.zone,
                    lbl.CAPACITY_TYPE: instance.capacity_type,
                    lbl.ARCH: it.architecture,
                    lbl.OS: lbl.OS_LINUX,
                },
                annotations=(
                    {lbl.LAUNCH_TOKEN_ANNOTATION: instance.launch_token}
                    if instance.launch_token else {}
                ),
            ),
            spec=NodeSpec(provider_id=f"sim:///{instance.zone}/{instance.id}"),
            status=NodeStatus(capacity=dict(it.resources), allocatable=allocatable),
        )


class SimulatedCloudProvider(CloudProvider):
    """reference: aws/cloudprovider.go:53-188."""

    def __init__(self, api: Optional[SimCloudAPI] = None, clock=None):
        self.api = api or SimCloudAPI()
        self.subnet_provider = SubnetProvider(self.api, clock=clock)
        self.security_group_provider = SecurityGroupProvider(self.api, clock=clock)
        self.instance_type_provider = InstanceTypeProvider(
            self.api, self.subnet_provider, clock=clock
        )
        self.launch_template_provider = LaunchTemplateProvider(
            self.api, self.security_group_provider
        )
        self.instance_provider = InstanceProvider(
            self.api,
            self.instance_type_provider,
            self.subnet_provider,
            self.launch_template_provider,
        )
        from karpenter_tpu.resilience import MissTracker

        self._liveness = MissTracker(threshold=LIVENESS_MISS_THRESHOLD)

    @idempotent
    def create(self, request: NodeRequest) -> Node:
        # idempotent BY TOKEN: the launch token rides down to the fleet
        # call, where a committed token replays the recorded instance
        config = SimProviderConfig.deserialize(request.template.provider)
        return self.instance_provider.create(
            config, request, token=request.launch_token
        )

    @idempotent
    def delete(self, node: Node) -> None:
        self.instance_provider.delete(node)

    def list_instances(self) -> List[LiveInstance]:
        """Live inventory for the GC/adoption cross-check: every
        non-terminated instance with the launch token its create stamped."""
        return [
            LiveInstance(
                id=inst.id,
                launch_token=inst.launch_token,
                instance_type=inst.instance_type,
                zone=inst.zone,
                capacity_type=inst.capacity_type,
                created_at=inst.created_at,
                provider_id=f"sim:///{inst.zone}/{inst.id}",
            )
            for inst in self.api.list_instances()
            if inst.state != "terminated"
        ]

    @idempotent
    def get_instance_types(self, provider: Optional[Dict[str, Any]] = None) -> List[InstanceType]:
        return self.instance_type_provider.get(SimProviderConfig.deserialize(provider))

    def default(self, constraints: Constraints) -> None:
        """Vendor defaulting: capacity-type on-demand, arch amd64
        (reference: aws/apis/v1alpha1/provider_defaults.go:26-56)."""
        if not constraints.requirements.capacity_types():
            constraints.requirements = constraints.requirements.add(
                NodeSelectorRequirement(
                    key=lbl.CAPACITY_TYPE, operator="In", values=[lbl.CAPACITY_TYPE_ON_DEMAND]
                )
            )
        if not constraints.requirements.architectures():
            constraints.requirements = constraints.requirements.add(
                NodeSelectorRequirement(key=lbl.ARCH, operator="In", values=[lbl.ARCH_AMD64])
            )

    def validate(self, constraints: Constraints) -> List[str]:
        return SimProviderConfig.deserialize(constraints.provider).validate()

    @idempotent
    def poll_disruptions(self) -> List[DisruptionNotice]:
        """DisruptionSource: drain the control plane's event bus (works
        identically against the in-process ``SimCloudAPI`` and the HTTP
        client's ``GET /v1/events``)."""
        return self.api.poll_disruptions()

    def requeue_disruption(self, notice: DisruptionNotice) -> bool:
        """Fleet routing: push a notice drained by the wrong replica back
        onto the event bus for the shard owner's next poll — in-process via
        the double's injector, over the wire via POST /v1/events/requeue
        (both expose ``send_disruption_notice``)."""
        sender = getattr(self.api, "send_disruption_notice", None)
        if sender is None:
            return False
        sender(notice)
        return True

    def instance_gone(self, node: Node) -> Optional[bool]:
        """Node liveness with flake debouncing. ``describe_instances``
        silently drops unknown ids, so a single missing id is ambiguous:
        flaky response or terminated instance? A ``terminated`` state (or a
        typed NotFound) answers True immediately; a bare miss answers True
        only after LIVENESS_MISS_THRESHOLD consecutive misses; an errored
        describe answers None (unknown) without advancing the count."""
        instance_id = node.spec.provider_id.rsplit("/", 1)[-1]
        try:
            found = self.api.describe_instances([instance_id])
        except InstanceNotFoundError:
            self._liveness.forget(instance_id)
            return True
        except Exception:
            return None  # the probe failed; that is not a miss
        if found:
            if found[0].state == "terminated":
                self._liveness.forget(instance_id)
                return True
            self._liveness.observe(instance_id, present=True)
            return False
        gone = self._liveness.observe(instance_id, present=False)
        if gone:
            self._liveness.forget(instance_id)
        return gone

    def name(self) -> str:
        return "simulated"
