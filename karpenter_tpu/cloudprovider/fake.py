"""Fake cloud provider + synthetic catalogs — a first-class test deliverable
(reference: pkg/cloudprovider/fake).

``FakeCloudProvider.create`` records every NodeRequest and fabricates a ready
node from the *first* (cheapest, since the solver sorted) instance-type
option, choosing the first offering compatible with the request's
zone/capacity-type requirements (reference: fake/cloudprovider.go:52-90).
"""

from __future__ import annotations

import itertools
import threading
import time
from typing import Any, Dict, FrozenSet, List, Optional

from karpenter_tpu.api import labels as lbl
from karpenter_tpu.api.objects import Node, NodeSpec, NodeStatus, ObjectMeta
from karpenter_tpu.cloudprovider.types import (
    CloudProvider,
    InstanceType,
    LiveInstance,
    NodeRequest,
    Offering,
)
from karpenter_tpu.interruption.types import PREEMPTION, DisruptionNotice, NoticeQueue
from karpenter_tpu.resilience.markers import idempotent
from karpenter_tpu.utils import resources as res

_name_counter = itertools.count(1)

DEFAULT_ZONES = ["test-zone-1", "test-zone-2", "test-zone-3"]

DEFAULT_OFFERINGS = [
    Offering("spot", "test-zone-1"),
    Offering("spot", "test-zone-2"),
    Offering("on-demand", "test-zone-1"),
    Offering("on-demand", "test-zone-2"),
    Offering("on-demand", "test-zone-3"),
]


def new_instance_type(
    name: str,
    offerings: Optional[List[Offering]] = None,
    architecture: str = "amd64",
    operating_systems: FrozenSet[str] = frozenset({"linux", "windows", "darwin"}),
    resources: Optional[Dict[str, float]] = None,
    overhead: Optional[Dict[str, float]] = None,
    price: Optional[float] = None,
) -> InstanceType:
    """Parameterizable fake type with the reference's defaults
    (reference: fake/instancetype.go:32-76): 4 cpu / 4Gi / 5 pods,
    100m+10Mi overhead, 5 offerings over 3 zones."""
    resources = dict(resources or {})
    resources.setdefault(res.CPU, 4.0)
    resources.setdefault(res.MEMORY, res.parse_quantity("4Gi"))
    resources.setdefault(res.PODS, 5.0)
    return InstanceType(
        name=name,
        offerings=list(offerings) if offerings else list(DEFAULT_OFFERINGS),
        architecture=architecture,
        operating_systems=operating_systems,
        resources=resources,
        overhead=dict(overhead) if overhead is not None else {res.CPU: 0.1, res.MEMORY: res.parse_quantity("10Mi")},
        price=price,
    )


def instance_types(total: int) -> List[InstanceType]:
    """n types with linearly scaling cpu/mem/pods — the benchmark catalog
    (reference: fake/instancetype.go:117-130)."""
    return [
        new_instance_type(
            f"fake-it-{i}",
            resources={
                res.CPU: float(i + 1),
                res.MEMORY: res.parse_quantity(f"{(i + 1) * 2}Gi"),
                res.PODS: float((i + 1) * 10),
            },
        )
        for i in range(total)
    ]


def instance_types_tradeoff(total: int) -> List[InstanceType]:
    """n types with ANTI-correlated cpu/mem (cpu-heavy ↔ mem-heavy ends of
    the range): every type is Pareto-optimal, so the encoded capacity
    frontier is ``total`` wide. The linear/assorted catalogs are
    Pareto-degenerate (F=1 — each type dominates the previous), which never
    exercises the solver's multi-frontier (v2) region."""
    return [
        new_instance_type(
            f"trade-it-{i}",
            resources={
                res.CPU: float(2 + i),
                res.MEMORY: res.parse_quantity(f"{2 * (total - i)}Gi"),
                res.PODS: 110.0,
            },
        )
        for i in range(total)
    ]


def instance_types_assorted() -> List[InstanceType]:
    """Full cross product 7cpu×8mem×3zones×2ct×2os×2arch = 1,344 unique types
    — drives price-optimality tests (reference: fake/instancetype.go:79-110)."""
    out: List[InstanceType] = []
    for cpu in [1, 2, 4, 8, 16, 32, 64]:
        for mem in [1, 2, 4, 8, 16, 32, 64, 128]:
            for zone in DEFAULT_ZONES:
                for ct in [lbl.CAPACITY_TYPE_SPOT, lbl.CAPACITY_TYPE_ON_DEMAND]:
                    for os_ in ["linux", "windows"]:
                        for arch in [lbl.ARCH_AMD64, lbl.ARCH_ARM64]:
                            out.append(
                                new_instance_type(
                                    f"{cpu}-cpu-{mem}-mem-{arch}-{os_}-{zone}-{ct}",
                                    architecture=arch,
                                    operating_systems=frozenset({os_}),
                                    resources={
                                        res.CPU: float(cpu),
                                        res.MEMORY: res.parse_quantity(f"{mem}Gi"),
                                    },
                                    offerings=[Offering(ct, zone)],
                                )
                            )
    return out


def default_catalog() -> List[InstanceType]:
    """The fake provider's built-in catalog
    (reference: fake/cloudprovider.go:92-140)."""
    return [
        new_instance_type("default-instance-type"),
        new_instance_type("pod-eni-instance-type", resources={res.AWS_POD_ENI: 1.0}),
        new_instance_type(
            "small-instance-type",
            resources={res.CPU: 2.0, res.MEMORY: res.parse_quantity("2Gi")},
        ),
        new_instance_type("nvidia-gpu-instance-type", resources={res.NVIDIA_GPU: 2.0}),
        new_instance_type("amd-gpu-instance-type", resources={res.AMD_GPU: 2.0}),
        new_instance_type("aws-neuron-instance-type", resources={res.AWS_NEURON: 2.0}),
        new_instance_type(
            "arm-instance-type",
            architecture="arm64",
            operating_systems=frozenset({"ios", "linux", "windows", "darwin"}),
            resources={res.CPU: 16.0, res.MEMORY: res.parse_quantity("128Gi")},
        ),
    ]


class FakeCloudProvider(CloudProvider):
    def __init__(self, instance_types: Optional[List[InstanceType]] = None):
        self.instance_types: Optional[List[InstanceType]] = instance_types
        self.create_calls: List[NodeRequest] = []
        self.delete_calls: List[str] = []
        self.disruptions = NoticeQueue()
        self._mu = threading.Lock()
        # launch-token ledger: token -> the node that create returned, and
        # the live-instance inventory list_instances serves. Same token →
        # same node, never a second launch (the idempotent-create contract).
        self._token_nodes: Dict[str, Node] = {}  # guarded-by: self._mu
        self._instances: Dict[str, LiveInstance] = {}  # guarded-by: self._mu

    @idempotent
    def create(self, request: NodeRequest) -> Node:
        token = request.launch_token
        with self._mu:
            self.create_calls.append(request)
            if token and token in self._token_nodes:
                return self._token_nodes[token]
        name = f"fake-node-{next(_name_counter)}"
        instance = request.instance_type_options[0]
        zone = capacity_type = ""
        reqs = request.template.requirements
        for o in instance.offerings:
            if reqs.capacity_types() and o.capacity_type in reqs.capacity_types() and o.zone in reqs.zones():
                zone, capacity_type = o.zone, o.capacity_type
                break
        node = Node(
            metadata=ObjectMeta(
                name=name,
                namespace="",
                labels={
                    lbl.TOPOLOGY_ZONE: zone,
                    lbl.INSTANCE_TYPE: instance.name,
                    lbl.CAPACITY_TYPE: capacity_type,
                },
                annotations=(
                    {lbl.LAUNCH_TOKEN_ANNOTATION: token} if token else {}
                ),
            ),
            spec=NodeSpec(provider_id=f"fake:///{name}/{zone}"),
            status=NodeStatus(
                allocatable={
                    res.PODS: instance.resources.get(res.PODS, 0.0),
                    res.CPU: instance.resources.get(res.CPU, 0.0),
                    res.MEMORY: instance.resources.get(res.MEMORY, 0.0),
                },
                capacity=dict(instance.resources),
            ),
        )
        with self._mu:
            if token:
                # a racer with the same token committed first: ITS node is
                # the one the token names (this fabricated double is dropped)
                racer = self._token_nodes.get(token)
                if racer is not None:
                    return racer
                self._token_nodes[token] = node
            self._instances[name] = LiveInstance(
                id=name,
                launch_token=token,
                instance_type=instance.name,
                zone=zone,
                capacity_type=capacity_type,
                created_at=time.time(),
                provider_id=node.spec.provider_id,
            )
        return node

    @idempotent
    def delete(self, node: Node) -> None:
        with self._mu:
            self.delete_calls.append(node.metadata.name)
            live = self._instances.pop(node.metadata.name, None)
            if live is not None and live.launch_token:
                self._token_nodes.pop(live.launch_token, None)

    def list_instances(self) -> List[LiveInstance]:
        with self._mu:
            return list(self._instances.values())

    @idempotent
    def get_instance_types(self, provider: Optional[Dict[str, Any]] = None) -> List[InstanceType]:
        if self.instance_types is not None:
            return self.instance_types
        return default_catalog()

    # -- DisruptionSource ---------------------------------------------------
    def preempt(
        self,
        node_name: str,
        grace_period_seconds: float = 120.0,
        kind: str = PREEMPTION,
        reason: str = "",
    ) -> DisruptionNotice:
        """Test/bench fault injector: announce that this node's capacity
        will be reclaimed in ``grace_period_seconds``."""
        notice = DisruptionNotice(
            kind=kind,
            node_name=node_name,
            grace_period_seconds=grace_period_seconds,
            reason=reason,
        )
        self.disruptions.push(notice)
        return notice

    @idempotent
    def poll_disruptions(self) -> List[DisruptionNotice]:
        return self.disruptions.drain()

    def requeue_disruption(self, notice: DisruptionNotice) -> bool:
        self.disruptions.push(notice)
        return True

    def name(self) -> str:
        return "fake"
