"""Latency decorator for cloud providers.

Mirrors ``pkg/cloudprovider/metrics/cloudprovider.go:37-93``: every
``CloudProvider`` method is wrapped in a duration histogram labeled
{controller, method, provider}. The controller label comes from a
contextvar the manager sets around each reconcile — the analog of the
reference's context injection (``utils/injection/injection.go:72-84``).
"""

from __future__ import annotations

import contextvars
import time
from typing import Any, Dict, List, Optional

from karpenter_tpu import metrics
from karpenter_tpu.api.objects import Node
from karpenter_tpu.api.provisioner import Constraints
from karpenter_tpu.cloudprovider.types import CloudProvider, InstanceType, NodeRequest

# Which controller's reconcile (or worker loop) is currently executing.
reconciling_controller: contextvars.ContextVar[str] = contextvars.ContextVar(
    "reconciling_controller", default=""
)


class MeteredCloudProvider(CloudProvider):
    """Wraps a provider so Create/Delete/GetInstanceTypes are all observed
    (reference: metrics/cloudprovider.go:66-93; replaces the round-1 inline
    timing that only covered create)."""

    def __init__(self, delegate: CloudProvider):
        self.delegate = delegate

    def _observe(self, method: str, start: float) -> None:
        metrics.CLOUDPROVIDER_DURATION.labels(
            controller=reconciling_controller.get(),
            method=method,
            provider=self.delegate.name(),
        ).observe(time.perf_counter() - start)

    def create(self, request: NodeRequest) -> Node:
        start = time.perf_counter()
        try:
            return self.delegate.create(request)
        finally:
            self._observe("create", start)

    def delete(self, node: Node) -> None:
        start = time.perf_counter()
        try:
            return self.delegate.delete(node)
        finally:
            self._observe("delete", start)

    def get_instance_types(self, provider: Optional[Dict[str, Any]] = None) -> List[InstanceType]:
        start = time.perf_counter()
        try:
            return self.delegate.get_instance_types(provider)
        finally:
            self._observe("get_instance_types", start)

    def poll_disruptions(self):
        """The DisruptionSource poll is a real control-plane call for wire
        providers — observe it like create/delete."""
        start = time.perf_counter()
        try:
            return self.delegate.poll_disruptions()
        finally:
            self._observe("poll_disruptions", start)

    # webhook hooks + name pass through unmetered, as in the reference
    def default(self, constraints: Constraints) -> None:
        return self.delegate.default(constraints)

    def validate(self, constraints: Constraints) -> List[str]:
        return self.delegate.validate(constraints)

    def name(self) -> str:
        return self.delegate.name()


def decorate(provider: CloudProvider) -> CloudProvider:
    """Idempotent wrap (reference: metrics.Decorate)."""
    if isinstance(provider, MeteredCloudProvider):
        return provider
    return MeteredCloudProvider(provider)
