"""Latency + resilience decorator for cloud providers.

Mirrors ``pkg/cloudprovider/metrics/cloudprovider.go:37-93``: every
``CloudProvider`` method is wrapped in a duration histogram labeled
{controller, method, provider}. The controller label comes from a
contextvar the manager sets around each reconcile — the analog of the
reference's context injection (``utils/injection/injection.go:72-84``).

On top of the histograms, the decorator is where the resilience layer
(karpenter_tpu/resilience) meets the cloud: each control-plane method gets

- a :class:`~karpenter_tpu.resilience.CircuitBreaker` per
  (provider, method) — a dead control plane costs one windowed burst of
  failures, then callers fail fast (``BreakerOpen``) until a half-open
  probe finds it healthy again;
- a :class:`~karpenter_tpu.resilience.RetryPolicy` with decorrelated
  jitter and a hard per-operation deadline, capped by the active
  reconcile-round :class:`~karpenter_tpu.resilience.Budget`. Capacity
  signals (``InsufficientCapacityError``/stockouts) and validation errors
  are never retried — the ICE caches own those.

``create`` is retried here since the launch-token work — for delegates
whose own ``create`` carries the ``@idempotent`` marker (which karplint
ties to token awareness); a token-unaware delegate keeps the old
breaker-only, no-retry contract. Every request is stamped with a client
launch token before it reaches the vendor (the provisioning worker
journals the token first; this decorator backstops direct callers), and
all four in-tree providers replay a committed token instead of launching
twice — so a provider-level retry that lands after a
partially-completed launch (fleet committed, follow-up describe flaked)
re-finds the SAME instance rather than orphaning one no Node tracks.
Instances a crashed process still leaves behind are re-described by token
and adopted or reaped by the launch journal + GC controller
(docs/launch-journal.md). The read-path methods (describe/poll) and the
idempotent delete retry freely.
"""

from __future__ import annotations

import contextvars
import time
from typing import Any, Dict, List, Optional

from karpenter_tpu import metrics
from karpenter_tpu.api.objects import Node
from karpenter_tpu.api.provisioner import Constraints
from karpenter_tpu.cloudprovider.types import CloudProvider, InstanceType, NodeRequest
from karpenter_tpu.resilience import BreakerBoard, BreakerOpen, RetryPolicy
from karpenter_tpu.resilience.markers import idempotent, is_idempotent

# Which controller's reconcile (or worker loop) is currently executing.
reconciling_controller: contextvars.ContextVar[str] = contextvars.ContextVar(
    "reconciling_controller", default=""
)

# breaker defaults: a 10%-error chaos regime must NOT trip (windowed rate
# well under 0.5); a dead dependency trips within min_volume calls
BREAKER_WINDOW = 20
BREAKER_MIN_VOLUME = 5
BREAKER_FAILURE_RATE = 0.5
BREAKER_OPEN_SECONDS = 10.0


class MeteredCloudProvider(CloudProvider):
    """Wraps a provider so Create/Delete/GetInstanceTypes are all observed
    (reference: metrics/cloudprovider.go:66-93) and all pass through the
    per-method breaker + retry policy."""

    def __init__(self, delegate: CloudProvider, resilience: bool = True):
        self.delegate = delegate
        self.resilient = resilience
        self.breakers = BreakerBoard(
            window=BREAKER_WINDOW,
            min_volume=BREAKER_MIN_VOLUME,
            failure_rate=BREAKER_FAILURE_RATE,
            open_seconds=BREAKER_OPEN_SECONDS,
        )
        name = delegate.name()
        self._policies: Dict[str, RetryPolicy] = {
            # create retries are safe ONLY against a delegate that replays
            # launch tokens — its own @idempotent marker (karplint-enforced
            # to imply token awareness) is the opt-in. An out-of-tree
            # provider that never reads request.launch_token stays at the
            # old breaker-only contract: a retried create there would land
            # a second instance no Node tracks (the orphan this whole
            # module docstring is about).
            "create": RetryPolicy(
                max_attempts=3 if is_idempotent(delegate.create) else 1,
                deadline=20.0, dependency=f"{name}:create",
            ),
            "delete": RetryPolicy(max_attempts=3, deadline=15.0,
                                  dependency=f"{name}:delete"),
            "get_instance_types": RetryPolicy(max_attempts=3, deadline=15.0,
                                              dependency=f"{name}:get_instance_types"),
            "poll_disruptions": RetryPolicy(max_attempts=2, deadline=5.0,
                                            dependency=f"{name}:poll_disruptions"),
        }

    def _observe(self, method: str, start: float) -> None:
        metrics.CLOUDPROVIDER_DURATION.labels(
            controller=reconciling_controller.get(),
            method=method,
            provider=self.delegate.name(),
        ).observe(time.perf_counter() - start)

    def _guarded(self, method: str, fn, *args):
        """breaker(retry(fn)): the retry absorbs transient flakes inside ONE
        logical call; the breaker sees the logical outcome, so a dependency
        that only ever succeeds via retries still counts as healthy.

        Every call runs under a ``cloud.<method>`` span. A breaker-open
        fast-fail never reaches the control plane, so it VANISHES from the
        duration histogram — it is counted
        (``karpenter_cloudprovider_breaker_shortcircuit_total``) and tagged
        ``short_circuit=true`` on both this span and its parent, so a
        traced launch with a gap explains itself."""
        from karpenter_tpu import obs

        start = time.perf_counter()
        with obs.tracer().span(
            f"cloud.{method}",
            attrs={"provider": self.delegate.name(), "method": method},
        ) as span:
            try:
                if not self.resilient:
                    return fn(*args)
                breaker = self.breakers.get(f"{self.delegate.name()}:{method}")
                if not breaker.allow():
                    metrics.CLOUDPROVIDER_BREAKER_SHORTCIRCUIT.labels(
                        provider=self.delegate.name(), method=method
                    ).inc()
                    span.set_attribute("short_circuit", True)
                    if span.parent is not None:
                        span.parent.set_attribute("short_circuit", True)
                    raise BreakerOpen(breaker.dependency, breaker.open_seconds)
                try:
                    result = self._policies[method].call(fn, *args)
                except BreakerOpen:
                    raise
                except Exception as e:
                    # breaker state tracks AVAILABILITY: a deterministic answer
                    # (ICE/stockout, validation) means the dependency responded —
                    # an ICE storm must sideline offerings (the 45s cache), never
                    # open the create breaker and block the recovery launches
                    from karpenter_tpu.resilience import default_retryable

                    if default_retryable(e):
                        breaker.record_failure()
                    else:
                        breaker.record_success()
                    raise
                breaker.record_success()
                return result
            finally:
                self._observe(method, start)

    @idempotent
    def create(self, request: NodeRequest) -> Node:
        # idempotent BY TOKEN: a request arriving without a launch token
        # (direct callers; the provisioning worker journals its own first)
        # is stamped here, so every retry below replays one logical launch
        if request is not None and not getattr(request, "launch_token", ""):
            import dataclasses
            import uuid

            request = dataclasses.replace(request, launch_token=uuid.uuid4().hex)
        return self._guarded("create", self.delegate.create, request)

    @idempotent
    def delete(self, node: Node) -> None:
        return self._guarded("delete", self.delegate.delete, node)

    @idempotent
    def get_instance_types(self, provider: Optional[Dict[str, Any]] = None) -> List[InstanceType]:
        return self._guarded("get_instance_types", self.delegate.get_instance_types, provider)

    @idempotent
    def poll_disruptions(self):
        """The DisruptionSource poll is a real control-plane call for wire
        providers — observe it like create/delete. An open breaker yields
        an empty poll, not an exception: the interruption loop keeps its
        cadence and picks the stream back up when the breaker closes."""
        try:
            return self._guarded("poll_disruptions", self.delegate.poll_disruptions)
        except BreakerOpen:
            return []

    def instance_gone(self, node: Node) -> Optional[bool]:
        # liveness probes carry their own miss-threshold debouncing; a
        # breaker/retry layer here would only delay the reset-on-sighting
        return self.delegate.instance_gone(node)

    def list_instances(self):
        # the GC sweep's read path: unmetered passthrough (the sweep has
        # its own cadence; a raised list simply defers one GC round, and a
        # breaker here could mask a real leak for its whole open window)
        return self.delegate.list_instances()

    def requeue_disruption(self, notice) -> bool:
        # a local re-offer, not a metered control-plane call
        return self.delegate.requeue_disruption(notice)

    # webhook hooks + name pass through unmetered, as in the reference
    def default(self, constraints: Constraints) -> None:
        return self.delegate.default(constraints)

    def validate(self, constraints: Constraints) -> List[str]:
        return self.delegate.validate(constraints)

    def name(self) -> str:
        return self.delegate.name()


def decorate(provider: CloudProvider, resilience: bool = True) -> CloudProvider:
    """Idempotent wrap (reference: metrics.Decorate)."""
    if isinstance(provider, MeteredCloudProvider):
        return provider
    return MeteredCloudProvider(provider, resilience=resilience)
