"""Disruption event model: cloud-initiated node loss, typed.

The reference snapshot (v0.8.0) has no interruption handling — nodes retire
only when empty or expired; the project's own next major feature was a
native interruption controller that drains ahead of the termination notice
(the SQS/EventBridge consumer that later shipped as
``pkg/controllers/interruption``). This module is the vendor-neutral core
of that subsystem: a ``DisruptionNotice`` describes one cloud-initiated
disruption (spot preemption, maintenance window, capacity reclaim) with the
grace period the cloud promises before the capacity disappears, and
``DisruptionSource`` is the poll protocol every cloud provider implements
(``fake``, ``simulated``, ``gke``, and both HTTP clients).

Poll semantics are drain-the-queue: each ``poll_disruptions()`` call
returns every notice that arrived since the previous call and removes them
from the source — the controller is the only consumer, so at-most-once
delivery per process is the contract (a dropped notice re-manifests as the
node vanishing, which the node lifecycle already survives).
"""

from __future__ import annotations

import abc
import threading
from dataclasses import dataclass
from typing import Any, Dict, List

# Notice kinds — the vocabulary every provider maps its own event bus into
# (EC2 spot interruption / GCE preemption → PREEMPTION, scheduled
# maintenance → MAINTENANCE, capacity-pool reclaim → CAPACITY_RECLAIM).
PREEMPTION = "preemption"
MAINTENANCE = "maintenance"
CAPACITY_RECLAIM = "capacity-reclaim"

KINDS = (PREEMPTION, MAINTENANCE, CAPACITY_RECLAIM)

# Vendor default when a notice carries no grace period: the 2-minute spot
# interruption warning both EC2 and GCE give.
DEFAULT_GRACE_PERIOD_SECONDS = 120.0


@dataclass(frozen=True)
class DisruptionNotice:
    """One cloud-initiated disruption of one node.

    ``node_name`` is the CLUSTER node name (``metadata.name``) — every
    vendor here names its Node objects after the instance, so the provider
    can emit cluster-addressable notices without a reverse lookup.
    ``grace_period_seconds`` is the cloud's promise: after that long the
    instance is gone whether or not the drain finished."""

    kind: str
    node_name: str
    grace_period_seconds: float = DEFAULT_GRACE_PERIOD_SECONDS
    issued_at: float = 0.0
    reason: str = ""

    def to_wire(self) -> Dict[str, Any]:
        """JSON shape served by the httpapi ``/events`` routes."""
        return {
            "kind": self.kind,
            "nodeName": self.node_name,
            "gracePeriodSeconds": self.grace_period_seconds,
            "issuedAt": self.issued_at,
            "reason": self.reason,
        }

    @staticmethod
    def from_wire(doc: Dict[str, Any]) -> "DisruptionNotice":
        return DisruptionNotice(
            kind=str(doc.get("kind", PREEMPTION)),
            node_name=str(doc.get("nodeName", "")),
            grace_period_seconds=float(
                doc.get("gracePeriodSeconds", DEFAULT_GRACE_PERIOD_SECONDS)
            ),
            issued_at=float(doc.get("issuedAt", 0.0)),
            reason=str(doc.get("reason", "")),
        )


class DisruptionSource(abc.ABC):
    """The provider-side half of the subsystem: something that can be
    polled for pending disruption notices. ``CloudProvider`` carries a
    default no-op implementation, so the controller can poll any provider;
    vendors opt in by returning real notices."""

    @abc.abstractmethod
    def poll_disruptions(self) -> List[DisruptionNotice]:
        """Return-and-clear every notice that arrived since the last poll."""


class NoticeQueue:
    """Thread-safe pending-notice buffer the provider doubles share: test
    harnesses and fault injectors ``push`` from any thread; the controller's
    poll ``drain``s. Deduplicates by (kind, node): a cloud that re-announces
    the same preemption every poll interval (as EC2's instance-action
    metadata does) must not restart the response each time."""

    def __init__(self) -> None:
        self._mu = threading.Lock()
        self._pending: List[DisruptionNotice] = []
        self._keys: set = set()

    def push(self, notice: DisruptionNotice) -> bool:
        """Queue a notice; returns False when an identical (kind, node)
        notice is already pending (the re-announcement case)."""
        key = (notice.kind, notice.node_name)
        with self._mu:
            if key in self._keys:
                return False
            self._keys.add(key)
            self._pending.append(notice)
            return True

    def drain(self) -> List[DisruptionNotice]:
        with self._mu:
            out, self._pending = self._pending, []
            self._keys.clear()
            return out

    def __len__(self) -> int:
        with self._mu:
            return len(self._pending)
