"""Interruption subsystem: preemption-aware node lifecycle.

Watches a cloud disruption-event stream (``DisruptionSource``) and
orchestrates the response — taint + cordon, Kubernetes event, proactive
replacement through the provisioning batcher, then finalizer-driven
termination under a grace-period deadline. See docs/interruption.md.
"""

from karpenter_tpu.interruption.orchestrator import Orchestrator, Response
from karpenter_tpu.interruption.types import (
    CAPACITY_RECLAIM,
    DEFAULT_GRACE_PERIOD_SECONDS,
    KINDS,
    MAINTENANCE,
    PREEMPTION,
    DisruptionNotice,
    DisruptionSource,
    NoticeQueue,
)

__all__ = [
    "CAPACITY_RECLAIM",
    "DEFAULT_GRACE_PERIOD_SECONDS",
    "DisruptionNotice",
    "DisruptionSource",
    "KINDS",
    "MAINTENANCE",
    "NoticeQueue",
    "Orchestrator",
    "PREEMPTION",
    "Response",
]
