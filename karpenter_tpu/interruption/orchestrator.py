"""The end-to-end response to one disruption notice.

Ordering guarantee (the subsystem's contract, asserted by
tests/test_interruption.py): on a notice the orchestrator

1. taints (``karpenter.sh/interruption=<kind>:NoSchedule``) and cordons the
   node in ONE merge patch, so no new pod lands on doomed capacity;
2. emits a Kubernetes Warning event (``kubectl describe node`` shows why
   the node went away);
3. **injects the node's reschedulable pods into the provisioning batcher
   BEFORE any eviction happens** — each pod is released from the node
   (nodeName cleared, marked Unschedulable) and handed straight to the
   first admitting provisioner worker, so replacement capacity is already
   launching while the old node still runs. There is no kubelet or
   ReplicaSet controller in this substrate: the pod OBJECT is the workload,
   and re-binding it to the replacement node IS the replacement;
4. hands the node to the existing termination controller (delete → the
   finalizer-driven cordon/drain/terminate path) with the deadline derived
   from the notice's grace period tracked by the interruption controller.

Because step 3 removes every reschedulable pod from the node before step 4
runs, the termination drain finds only pods that could never move
(do-not-evict, daemonset, static) — a clean preemption evicts nothing, and
``interruption_evicted_unready`` stays 0.

``force_terminate`` is the deadline path: the cloud is taking the capacity
regardless, so do-not-evict stops applying — remaining pods are counted as
evicted-without-replacement, force-drained, and the instance is deleted.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import List, Optional

from karpenter_tpu import metrics
from karpenter_tpu.api import labels as lbl
from karpenter_tpu.api.objects import Node, Pod, Taint
from karpenter_tpu.interruption.types import DisruptionNotice
from karpenter_tpu.kube.client import Cluster
from karpenter_tpu.utils import pod as podutil

logger = logging.getLogger("karpenter.interruption")


@dataclass
class Response:
    """What one ``handle()`` did — the controller tracks the deadline and
    the migrated pods' replacement lead times from this."""

    node_name: str
    deadline: float
    migrated: List[Pod] = field(default_factory=list)
    blocked: List[Pod] = field(default_factory=list)  # do-not-evict holdouts


class Orchestrator:
    def __init__(self, cluster: Cluster, cloud_provider, provisioning, termination):
        self.cluster = cluster
        self.cloud_provider = cloud_provider
        self.provisioning = provisioning  # ProvisioningController (submit hook)
        self.termination = termination  # TerminationController (terminator + force drain)
        # bench/test observability beside the prometheus counters
        self.evicted_unready = 0
        self.notices_handled = 0

    # -- the notice path ---------------------------------------------------
    def handle(self, notice: DisruptionNotice, on_release=None) -> Optional[Response]:
        """Run steps 1–4 for one notice; returns None when there is nothing
        to do (node unknown or already terminating — the dedup for a cloud
        that re-announces). ``on_release(pod)`` fires after each pod is
        released and BEFORE it enters the batcher, so the caller's
        replacement-lead-time tracking can never miss a fast re-bind."""
        node = self.cluster.try_get("nodes", notice.node_name, namespace="")
        if node is None or node.metadata.deletion_timestamp is not None:
            return None
        self.notices_handled += 1
        now = self.cluster.clock()
        deadline = now + max(float(notice.grace_period_seconds), 0.0)
        from karpenter_tpu import obs

        # the taint→replace→drain response as one trace: each step is a
        # child span, and the replacement solves the migrated pods trigger
        # nest under interruption.replace via the contextvar
        with obs.tracer().span(
            "interruption.notice",
            attrs={
                "kind": notice.kind,
                "node": node.metadata.name,
                "grace_s": float(notice.grace_period_seconds),
            },
        ) as sp:
            with obs.tracer().span("interruption.taint_cordon"):
                self._taint_and_cordon(node, notice)
            from karpenter_tpu.kube.events import recorder_for

            recorder_for(self.cluster).event(
                "Node", node.metadata.name, "InterruptionNotice",
                f"{notice.kind} notice ({notice.reason or 'cloud-initiated'}): "
                f"grace {notice.grace_period_seconds:g}s; replacing pods proactively",
                type="Warning",
            )
            with obs.tracer().span("interruption.replace") as rep_sp:
                migrated, blocked = self._migrate(node, on_release)
                rep_sp.set_attribute("migrated", len(migrated))
                rep_sp.set_attribute("blocked", len(blocked))
            # only AFTER the replacement injection does the node enter the
            # termination path — this delete is the ordering guarantee's fence
            with obs.tracer().span("interruption.drain_handoff"):
                self.cluster.delete("nodes", node.metadata.name, namespace="")
            metrics.INTERRUPTION_DRAINS_STARTED.inc()
            sp.set_attribute("migrated", len(migrated))
        logger.info(
            "interruption: %s on %s (grace %gs) — %d pod(s) injected for "
            "replacement, %d blocked",
            notice.kind, node.metadata.name, notice.grace_period_seconds,
            len(migrated), len(blocked),
        )
        return Response(
            node_name=node.metadata.name, deadline=deadline,
            migrated=migrated, blocked=blocked,
        )

    # -- the consolidation path --------------------------------------------
    def consolidate(
        self, node: Node, decision_id: str = "", on_release=None
    ) -> Optional[Response]:
        """Retire one node VOLUNTARILY with the same ordering guarantee as
        a notice: taint+cordon (taint value ``consolidation``, so a
        mid-wave crash leaves a recognizable breadcrumb the journal replay
        un-cordons) → event → replacement injection BEFORE any eviction →
        drain handoff. The consolidation controller journals the whole
        wave before calling this per victim; ``decision_id`` rejoins the
        audit record that proposed the wave. Unlike ``handle`` there is no
        cloud deadline — the only clock on a voluntary wave is the
        controller's settle timeout — and do-not-evict pods cannot appear
        (plan-time screening excludes their nodes), but if one slips in it
        still blocks exactly as on the notice path."""
        node = self.cluster.try_get("nodes", node.metadata.name, namespace="")
        if node is None or node.metadata.deletion_timestamp is not None:
            return None
        from karpenter_tpu import obs

        notice = DisruptionNotice(
            kind="consolidation", node_name=node.metadata.name,
            grace_period_seconds=0.0, reason="consolidation re-pack",
        )
        with obs.tracer().span(
            "consolidation.move",
            attrs={"node": node.metadata.name, "decision_id": decision_id},
        ) as sp:
            with obs.tracer().span("interruption.taint_cordon"):
                self._taint_and_cordon(node, notice)
            from karpenter_tpu.kube.events import recorder_for

            recorder_for(self.cluster).event(
                "Node", node.metadata.name, "ConsolidationDrain",
                "consolidation re-pack is retiring this node; "
                "replacing pods proactively",
                type="Warning", decision_id=decision_id,
            )
            with obs.tracer().span("interruption.replace") as rep_sp:
                migrated, blocked = self._migrate(node, on_release)
                rep_sp.set_attribute("migrated", len(migrated))
                rep_sp.set_attribute("blocked", len(blocked))
            with obs.tracer().span("interruption.drain_handoff"):
                self.cluster.delete("nodes", node.metadata.name, namespace="")
            sp.set_attribute("migrated", len(migrated))
        logger.info(
            "consolidation: retiring %s — %d pod(s) injected for "
            "replacement, %d blocked",
            node.metadata.name, len(migrated), len(blocked),
        )
        return Response(
            node_name=node.metadata.name, deadline=0.0,
            migrated=migrated, blocked=blocked,
        )

    def _taint_and_cordon(self, node: Node, notice: DisruptionNotice) -> None:
        """One merge patch: interruption taint + cordon + ensure the
        termination finalizer (a self-registered node may not carry it yet,
        and without it the delete below would skip the drain path).

        RFC 7386 replaces the taints array wholesale, so the patch carries
        the FULL list — the node's current taints with the interruption
        taint upserted (kube.patch's RMW idiom; re-tainting an already
        noticed node is a no-op replace of the same entry)."""
        from karpenter_tpu.kube.patch import upsert_taint
        from karpenter_tpu.kube.serde import taint_to_wire

        taints_wire = upsert_taint(
            [taint_to_wire(t) for t in node.spec.taints],
            taint_to_wire(
                Taint(
                    key=lbl.INTERRUPTION_TAINT_KEY,
                    value=notice.kind,
                    effect="NoSchedule",
                )
            ),
        )
        finalizers = list(node.metadata.finalizers)
        if lbl.TERMINATION_FINALIZER not in finalizers:
            finalizers.append(lbl.TERMINATION_FINALIZER)
        self.cluster.merge_patch(
            "nodes", node.metadata.name,
            {
                "spec": {
                    "unschedulable": True,
                    "taints": taints_wire,
                },
                "metadata": {"finalizers": finalizers},
            },
            namespace=node.metadata.namespace,
        )

    def _migrate(self, node: Node, on_release=None):
        """Release every reschedulable pod from the node and inject it into
        the provisioning batcher. Pods are released even when no worker
        admits them right now — a pending pod survives the node's death and
        the selection controller keeps retrying it, whereas a pod left
        bound is destroyed with the node."""
        migrated: List[Pod] = []
        blocked: List[Pod] = []
        for pod in self.cluster.pods_on_node(node.metadata.name):
            if pod.metadata.deletion_timestamp is not None:
                continue
            if podutil.is_owned_by_daemonset(pod) or podutil.is_owned_by_node(pod):
                continue  # per-node workloads don't migrate
            if pod.metadata.annotations.get(lbl.DO_NOT_EVICT_ANNOTATION) == "true":
                blocked.append(pod)  # honored until the grace deadline
                continue
            released = self._release(pod)
            if on_release is not None:
                on_release(released)
            worker = self.provisioning.submit(released) if self.provisioning else None
            if worker is None:
                logger.warning(
                    "no provisioner admits replacement pod %s; left pending "
                    "for selection to retry", released.key,
                )
            migrated.append(released)
        return migrated, blocked

    def _release(self, pod: Pod) -> Pod:
        """Unbind the pod and mark it Unschedulable so the provisioning
        re-verify (``is_provisionable``) accepts it — the same wire shape
        the kube-scheduler would leave on a pending pod. Returns the
        PATCHED object: the in-memory store mutates in place, but
        ``ApiCluster.merge_patch`` returns a fresh object without touching
        the caller's copy — injecting the stale one would fail the
        is_provisionable re-verify and silently skip the replacement."""
        conditions = [
            {"type": c.type, "status": c.status, "reason": c.reason or None}
            for c in pod.status.conditions
            if c.type != "PodScheduled"
        ]
        conditions.append(
            {"type": "PodScheduled", "status": "False", "reason": "Unschedulable"}
        )
        return self.cluster.merge_patch(
            "pods", pod.metadata.name,
            {"spec": {"nodeName": None}, "status": {"conditions": conditions}},
            namespace=pod.metadata.namespace,
        )

    # -- the deadline path -------------------------------------------------
    def force_terminate(self, node: Node) -> int:
        """The grace period is over: whatever still sits on the node is
        lost capacity-side, so count it, force-drain (do-not-evict no
        longer applies), and delete the instance + finalizer. Returns the
        number of pods that had no replacement ready."""
        left = [
            p for p in self.cluster.pods_on_node(node.metadata.name)
            if p.metadata.deletion_timestamp is None
            and not podutil.is_owned_by_daemonset(p)
            and not podutil.is_owned_by_node(p)
        ]
        if left:
            metrics.INTERRUPTION_EVICTED_UNREADY.inc(len(left))
            self.evicted_unready += len(left)
        from karpenter_tpu.kube.events import recorder_for

        recorder_for(self.cluster).event(
            "Node", node.metadata.name, "InterruptionDeadlineReached",
            f"grace period expired with {len(left)} pod(s) still aboard; "
            "forcing termination",
            type="Warning",
        )
        from karpenter_tpu import obs

        with obs.tracer().span(
            "interruption.force_terminate",
            attrs={"node": node.metadata.name, "pods_left": len(left)},
        ):
            terminator = self.termination.terminator
            terminator.cordon(node)
            terminator.drain(node, force=True)
            # The provider already announced this capacity is being
            # reclaimed, so an ownership/fence check proves nothing here
            # (PR-6/PR-11 fencing is for leader-driven mutations).
            # mutation-guard: exempt — cloud-notified interruption path
            terminator.terminate(node)
        logger.warning(
            "interruption deadline: force-terminated %s (%d pod(s) without "
            "replacement)", node.metadata.name, len(left),
        )
        return len(left)
