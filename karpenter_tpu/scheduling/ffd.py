"""First-fit-decreasing reference scheduler (the CPU path).

A faithful re-implementation of the reference's scheduling core
(``pkg/controllers/provisioning/scheduling/scheduler.go:64-137``,
``node.go:30-81``, ``nodeset.go:30-78``): sort pods by CPU-then-memory
descending, instance types by price ascending, inject topology decisions as
just-in-time NodeSelectors, then first-fit each pod into existing virtual
nodes — incrementally narrowing each node's surviving instance-type set — or
open a new one.

This backend is the in-process fallback and the parity oracle for the TPU
batch solver (``karpenter_tpu.solver``).
"""

from __future__ import annotations

import copy
import logging
import random
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

from karpenter_tpu.api.objects import Pod
from karpenter_tpu.api.provisioner import Constraints
from karpenter_tpu.api.requirements import Requirements
from karpenter_tpu.cloudprovider.requirements import filter_instance_types
from karpenter_tpu.cloudprovider.types import InstanceType
from karpenter_tpu.kube.client import Cluster
from karpenter_tpu.scheduling.topology import (
    Topology,
    restore_selectors,
    snapshot_selectors,
)
from karpenter_tpu.utils import pod as podutil
from karpenter_tpu.utils import resources as res

logger = logging.getLogger("karpenter.scheduling")


@dataclass
class VirtualNode:
    """A set of constraints + compatible pods + surviving instance types;
    becomes a real node after launch (reference: node.go:30-44)."""

    constraints: Constraints
    instance_type_options: List[InstanceType]
    pods: List[Pod] = field(default_factory=list)
    requests: Dict[str, float] = field(default_factory=dict)
    used_host_ports: set = field(default_factory=set)

    def add(self, pod: Pod) -> Optional[str]:
        """Try to place the pod; returns an error string or None on success
        (reference: node.go:46-66, plus host-port conflict enforcement the
        reference deferred — suite_test.go:1758)."""
        ports = podutil.host_ports(pod)
        if podutil.host_ports_conflict(ports, self.used_host_ports):
            return f"host port(s) already claimed on node: {sorted(ports)}"
        pod_reqs = Requirements.from_pod(pod)
        if self.pods:
            errs = self.constraints.requirements.compatible(pod_reqs)
            if errs:
                return "; ".join(errs)
        requirements = self.constraints.requirements.add(*pod_reqs.requirements)
        requests = res.merge(self.requests, res.requests_for_pods(pod))
        instance_types = filter_instance_types(self.instance_type_options, requirements, requests)
        if not instance_types:
            return (
                f"no instance type satisfied resources {res.to_string(res.requests_for_pods(pod))} "
                f"and requirements {requirements}"
            )
        self.pods.append(pod)
        self.instance_type_options = instance_types
        self.requests = requests
        self.constraints.requirements = requirements
        self.used_host_ports |= ports
        return None


def daemon_overhead(cluster: Cluster, constraints: Constraints) -> Dict[str, float]:
    """Resources of daemonsets that will land on these nodes
    (reference: nodeset.go:36-74)."""
    total: Dict[str, float] = {}
    for ds in cluster.daemonsets():
        pod = Pod(spec=copy.deepcopy(ds.pod_template))
        # validate_pod covers both the taint toleration and the requirement
        # compatibility filters the reference applies.
        if constraints.validate_pod(pod):
            continue
        total = res.merge(total, res.requests_for_pods(pod))
    return total


def sort_pods_ffd_with_statics(pods: Sequence[Pod]):
    """FFD sort returning (sorted pods, their statics in the same order) so
    callers share one statics pass across sort -> inject -> encode."""
    import numpy as np

    from karpenter_tpu.scheduling.statics import statics

    import operator

    n = len(pods)
    sts = [statics(p) for p in pods]
    if n < 256:
        order = sorted(range(n), key=lambda i: (-sts[i].cpu, -sts[i].mem))
    else:
        cpu = np.fromiter(map(operator.attrgetter("cpu"), sts), dtype=np.float64, count=n)
        mem = np.fromiter(map(operator.attrgetter("mem"), sts), dtype=np.float64, count=n)
        # primary key last; lexsort is stable. tolist() first: indexing
        # Python lists with np.int64 scalars pays a boxing cost per element
        order = np.lexsort((-mem, -cpu)).tolist()
        getter = operator.itemgetter(*order)
        return list(getter(pods)), list(getter(sts))
    return [pods[i] for i in order], [sts[i] for i in order]


def sort_pods_ffd(pods: Sequence[Pod]) -> List[Pod]:
    """CPU-then-memory descending (reference: scheduler.go:116-137). Stable,
    like Go's sort.Slice on equal keys is not — but FFD only cares about the
    ordering of the keys."""
    return sort_pods_ffd_with_statics(pods)[0]


class FFDScheduler:
    """``solve`` returns virtual nodes for a batch of pending pods
    (reference: scheduler.go:64-108)."""

    def __init__(self, cluster: Cluster, rng: Optional[random.Random] = None):
        self.cluster = cluster
        self.topology = Topology(cluster, rng=rng)

    def solve(
        self,
        constraints: Constraints,
        instance_types: Sequence[InstanceType],
        pods: Sequence[Pod],
    ) -> List[VirtualNode]:
        constraints = constraints.clone()
        pods = sort_pods_ffd(pods)
        instance_types = sorted(instance_types, key=lambda it: it.effective_price())

        saved = snapshot_selectors(pods)
        try:
            self.topology.inject(constraints, list(pods))
            daemons = daemon_overhead(self.cluster, constraints)
            return self.solve_injected(constraints, instance_types, pods, daemons)
        finally:
            restore_selectors(pods, saved)

    def solve_injected(
        self,
        constraints: Constraints,
        instance_types: Sequence[InstanceType],
        pods: Sequence[Pod],
        daemons: Dict[str, float],
    ) -> List[VirtualNode]:
        """The packing loop alone — pods already FFD-sorted, topology already
        injected, types already price-sorted (shared entry for the TPU
        backend's fallback path)."""
        nodes: List[VirtualNode] = []
        unschedulable = 0
        for pod in pods:
            placed = False
            for node in nodes:
                if node.add(pod) is None:
                    placed = True
                    break
            if not placed:
                node = VirtualNode(
                    constraints=constraints.clone(),
                    instance_type_options=list(instance_types),
                    requests=dict(daemons),
                )
                err = node.add(pod)
                if err is None:
                    nodes.append(node)
                else:
                    unschedulable += 1
                    logger.error("Scheduling pod %s, %s", pod.key, err)
        if unschedulable:
            logger.error("Failed to schedule %d pods", unschedulable)
        return nodes
