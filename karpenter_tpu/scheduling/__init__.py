from karpenter_tpu.scheduling.ffd import VirtualNode, FFDScheduler  # noqa: F401
from karpenter_tpu.scheduling.scheduler import Scheduler  # noqa: F401
