"""Per-pod static scheduling facts, computed once per pod lifetime.

A 10k-pod solve used to re-derive the same per-pod facts on every pass —
requests for the FFD sort, canonical cores for the encode, affinity/spread
terms for the topology grouping, host-port claims for the bucketing — each a
Python loop over the pod's spec. All of it is a pure function of the spec,
and specs are immutable while a pod is pending (the one mutator, preference
relaxation, replaces ``spec.affinity`` wholesale), so it is computed once
and memoized on the pod object here.

Validity is checked structurally on access: the memo stores the raw
nodeSelector items and the affinity object's identity; either changing
recomputes. ``Preferences.relax`` replacing ``spec.affinity`` therefore
invalidates automatically.

The canonicalization here MUST fold exactly like ``Requirements.from_pod``
(nodeSelector + heaviest preferred node-affinity term + first required
OR-term — reference: requirements.go:55-75) and split hostname exactly like
``signature.pod_core_and_hostname``; the solver-parity suite pins this.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from karpenter_tpu.api import labels as lbl
from karpenter_tpu.api.objects import Pod
from karpenter_tpu.utils import pod as podutil
from karpenter_tpu.utils import resources as res

# keys whose per-domain narrowing topology injection consults
NARROWED_KEYS = (lbl.TOPOLOGY_ZONE, lbl.HOSTNAME)


class PodStatics:
    __slots__ = (
        "sel_raw",          # tuple(pod.spec.node_selector.items()) — validity token
        "sel_ref",          # the node_selector dict itself — identity token
        "aff_ref",          # the affinity object itself — identity token
        "core0",            # canonical core with no injected decisions
        "hostname0",        # hostname with no injected decisions
        "aff_entries",      # folded affinity (key, op, values) minus hostname
        "aff_hostname",     # hostname from FOLDED affinity terms (In, len 1)
        "pinned_aff_hostname",  # first In-len-1 hostname across ALL required terms
        "req",              # requests dict (incl. pods count)
        "req_key",          # tuple(sorted(req.items())) — vector-cache key
        "extra_res",        # resource names outside the reserved axes
        "cpu", "mem",       # FFD sort keys
        "host_ports",       # frozenset of (ip, port, proto) claims
        "labels_key",       # tuple(sorted(metadata.labels.items()))
        "aff_terms",        # tuple of (group_key, term, anti) for supported keys
        "spreads",          # tuple of (group_key, constraint)
        "topo_any",         # bool: any aff_terms/spreads/host_ports (discovery skip)
        "topo_code",        # int id of the (aff keys, spread keys, ports) class;
                            # 0 = no topology, -1 = registry overflow (slow path)
        "key_entries",      # {key: ((op, values_tuple), ...)} for NARROWED_KEYS
        "constrains",       # frozenset of keys the spec itself narrows
        "merge_tid",        # interned id of (sel_raw, aff_entries, aff_hostname)
        "req_tid",          # interned id of req_key
    )


# value-interning tables: template pods share (selector, affinity, requests)
# BY VALUE; interning to a canonical tuple OBJECT at statics-build time lets
# per-solve memos key on object identity (id()) instead of hashing nested
# tuples per pod. Identity keys stay valid even if the table is pruned: a
# live PodStatics keeps its canonical object alive, so the id cannot be
# recycled out from under a memo built during that statics' lifetime.
_merge_interns: Dict[Tuple, Tuple] = {}
_req_interns: Dict[Tuple, Tuple] = {}
_INTERN_MAX = 1 << 20

# topology-class registry: pods whose (affinity group keys, spread group
# keys, has-ports) agree are distributed to the same topology groups, so
# discovery can bucket a batch by ONE int per pod and gather members with
# numpy instead of 10k Python appends. Codes live in statics memos, so the
# table is never cleared — it is capped instead (code -1 = per-pod path).
# The lock makes code assignment atomic: statics are built concurrently
# from the selection reconcile pool, and two classes sharing one code
# would silently merge their topology groups in discovery.
import threading as _threading

_topo_classes: Dict[Tuple, int] = {}  # guarded-by: _topo_lock
_topo_lock = _threading.Lock()
_TOPO_CLASS_MAX = 1 << 16


def _intern(table: Dict[Tuple, Tuple], key: Tuple) -> Tuple:
    hit = table.get(key)
    if hit is not None:
        return hit
    if len(table) >= _INTERN_MAX:
        table.clear()
    table[key] = key
    return key


def _selector_key(sel) -> Tuple:
    if sel is None:
        return ()
    cached = getattr(sel, "_canon_key", None)
    if cached is not None:
        return cached
    key = (
        tuple(sorted(sel.match_labels.items())),
        tuple((e.key, e.operator, tuple(e.values)) for e in sel.match_expressions),
    )
    try:
        sel._canon_key = key
    except AttributeError:
        pass
    return key


def _affinity_key(namespace: str, term, anti: bool) -> Tuple:
    ns = tuple(sorted(term.namespaces)) if term.namespaces else (namespace,)
    return (anti, ns, term.topology_key, _selector_key(term.label_selector))


def _group_key(namespace: str, c) -> Tuple:
    return (namespace, c.max_skew, c.topology_key, c.when_unsatisfiable,
            _selector_key(c.label_selector))


SUPPORTED_AFFINITY_KEYS = (lbl.HOSTNAME, lbl.TOPOLOGY_ZONE)


def _build(pod: Pod) -> PodStatics:
    st = PodStatics()
    spec = pod.spec
    st.sel_raw = tuple(spec.node_selector.items())
    st.sel_ref = spec.node_selector
    st.aff_ref = spec.affinity

    # -- canonical core + hostname (mirrors signature.pod_core_and_hostname)
    reqs: List[Tuple[str, str, Tuple[str, ...]]] = []
    hostname: Optional[str] = None
    key_entries: Dict[str, list] = {}
    constrains = set()
    for key, value in st.sel_raw:
        key = lbl.NORMALIZED_LABELS.get(key, key)
        if key in lbl.IGNORED_LABELS:
            continue
        constrains.add(key)
        if key in NARROWED_KEYS:
            key_entries.setdefault(key, []).append(("In", (value,)))
        if key == lbl.HOSTNAME:
            hostname = value
            continue
        reqs.append((key, "In", (value,)))

    aff_entries: List[Tuple[str, str, Tuple[str, ...]]] = []
    aff_hostname: Optional[str] = None
    pinned_aff_hostname: Optional[str] = None
    aff = spec.affinity
    if aff is not None and aff.node_affinity is not None:
        na = aff.node_affinity
        folded = []
        if na.preferred:
            heaviest = max(na.preferred, key=lambda t: t.weight)
            folded.extend(heaviest.preference.match_expressions)
        if na.required:
            folded.extend(na.required[0].match_expressions)
        for t in folded:
            key = lbl.NORMALIZED_LABELS.get(t.key, t.key)
            if key in lbl.IGNORED_LABELS:
                continue
            constrains.add(key)
            if key in NARROWED_KEYS:
                key_entries.setdefault(key, []).append((t.operator, tuple(t.values)))
            if key == lbl.HOSTNAME and t.operator == "In" and len(t.values) == 1:
                aff_hostname = t.values[0]
                continue
            aff_entries.append((key, t.operator, tuple(t.values)))
        # _pinned_hostname scans ALL required terms (not just the folded
        # first), in order, for an In-len-1 hostname
        for term in na.required:
            for r in term.match_expressions:
                if r.key == lbl.HOSTNAME and r.operator == "In" and len(r.values) == 1:
                    pinned_aff_hostname = r.values[0]
                    break
            if pinned_aff_hostname is not None:
                break
        # every OTHER key mentioned anywhere also counts as "constrained"
        # for the spread fast-path gate (topology._pod_constrains semantics)
        for term in na.required:
            for r in term.match_expressions:
                constrains.add(lbl.NORMALIZED_LABELS.get(r.key, r.key))
        for pref in na.preferred:
            for r in pref.preference.match_expressions:
                constrains.add(lbl.NORMALIZED_LABELS.get(r.key, r.key))

    if aff_hostname is not None:
        hostname = aff_hostname
    st.core0 = tuple(sorted(reqs + aff_entries))
    st.hostname0 = hostname
    st.aff_entries = tuple(aff_entries)
    st.aff_hostname = aff_hostname
    st.pinned_aff_hostname = pinned_aff_hostname
    st.key_entries = {k: tuple(v) for k, v in key_entries.items()}
    st.constrains = frozenset(constrains)

    # -- resources (shares the requests memo with utils.resources)
    st.req = res.requests_for_pods(pod)
    st.req_key = tuple(sorted(st.req.items()))
    st.extra_res = frozenset(k for k in st.req if k not in res.AXIS_INDEX)
    st.cpu = st.req.get(res.CPU, 0.0)
    st.mem = st.req.get(res.MEMORY, 0.0)

    st.host_ports = frozenset(podutil.host_ports(pod))
    st.labels_key = tuple(sorted(pod.metadata.labels.items()))
    st.merge_tid = _intern(_merge_interns, (st.sel_raw, st.aff_entries, st.aff_hostname))
    st.req_tid = _intern(_req_interns, st.req_key)

    # -- topology group membership
    ns = pod.metadata.namespace
    terms = []
    if aff is not None:
        if aff.pod_affinity is not None:
            terms += [(t, False) for t in aff.pod_affinity.required]
        if aff.pod_anti_affinity is not None:
            terms += [(t, True) for t in aff.pod_anti_affinity.required]
    st.aff_terms = tuple(
        (_affinity_key(ns, t, anti), t, anti)
        for t, anti in terms
        if t.topology_key in SUPPORTED_AFFINITY_KEYS
    )
    st.spreads = tuple(
        (_group_key(ns, c), c) for c in spec.topology_spread_constraints
    )
    st.topo_any = bool(st.aff_terms or st.spreads or st.host_ports)
    if st.topo_any:
        ckey = (
            tuple(k for k, _, _ in st.aff_terms),
            tuple(k for k, _ in st.spreads),
            bool(st.host_ports),
        )
        code = _topo_classes.get(ckey)
        if code is None:
            with _topo_lock:
                code = _topo_classes.get(ckey)
                if code is None:
                    if len(_topo_classes) >= _TOPO_CLASS_MAX:
                        code = -1  # registry full: per-pod discovery path
                    else:
                        code = len(_topo_classes) + 1
                        _topo_classes[ckey] = code
        st.topo_code = code
    else:
        st.topo_code = 0
    return st


def statics(pod: Pod) -> PodStatics:
    """The pod's memoized statics, recomputed if the selector or the
    affinity object changed since last computed.

    Validity fast path is by object identity (the memo holds a reference,
    so the identity cannot be recycled): every selector write in this
    codebase REPLACES the dict (``{**sel, k: v}``) — the convention
    ``DomainPlan.materialize`` follows — so an unchanged dict object proves
    an unchanged selector. On identity mismatch (e.g. restore_selectors
    swapped the original dict back) the contents are compared before
    recomputing."""
    spec = pod.spec
    st = getattr(pod, "_solve_statics", None)
    if st is not None and st.aff_ref is spec.affinity:
        if st.sel_ref is spec.node_selector:
            return st
        if st.sel_raw == tuple(spec.node_selector.items()):
            st.sel_ref = spec.node_selector
            return st
    st = _build(pod)
    try:
        pod._solve_statics = st
    except AttributeError:
        pass
    return st


def satisfies(entries, domain: str) -> bool:
    """Does this domain satisfy every (op, values) entry? — the per-domain
    form of Requirements' per-key set intersection (requirements.go:78-110:
    In intersects, NotIn subtracts, Exists keeps the universe)."""
    for op, values in entries:
        if op == "In":
            if domain not in values:
                return False
        elif op == "NotIn":
            if domain in values:
                return False
        elif op == "DoesNotExist":
            return False
        # Exists: no narrowing
    return True


# (merge-key, injected items) -> (core, hostname); the vocabulary of merged
# cores in one batch is small (template pods × assigned domains), so this
# global memo turns the per-pod canonicalization into a dict hit
_merged_core_cache: Dict[Tuple, Tuple] = {}
_MERGED_CORE_CACHE_MAX = 65536


def merged_core(st: PodStatics, inj_items: Tuple[Tuple[str, str], ...]):
    """Canonical (core, hostname) after overlaying injected topology
    decisions onto the pod's own selector — byte-identical to mutating
    ``spec.node_selector`` and re-running ``pod_core_and_hostname``."""
    key = (st.sel_raw, st.aff_entries, st.aff_hostname, inj_items)
    hit = _merged_core_cache.get(key)
    if hit is not None:
        return hit
    merged = dict(st.sel_raw)
    merged.update(inj_items)
    reqs: List[Tuple[str, str, Tuple[str, ...]]] = []
    hostname: Optional[str] = None
    for k, v in merged.items():
        k = lbl.NORMALIZED_LABELS.get(k, k)
        if k in lbl.IGNORED_LABELS:
            continue
        if k == lbl.HOSTNAME:
            hostname = v
            continue
        reqs.append((k, "In", (v,)))
    if st.aff_hostname is not None:
        hostname = st.aff_hostname
    out = (tuple(sorted(reqs + list(st.aff_entries))), hostname)
    if len(_merged_core_cache) >= _MERGED_CORE_CACHE_MAX:
        _merged_core_cache.clear()
    _merged_core_cache[key] = out
    return out
