"""Unschedulability oracle: prove every dropped pod is genuinely
unsatisfiable, not an artifact of the greedy topology pre-assignment.

The reference logs-and-drops unschedulable pods (scheduler.go:95-99) without
explanation. Here, the oracle independently re-derives — from the original
pod specs, the cluster state, and the provisioner constraints alone — the
exact set of pods NO schedule could place under the framework's declared
affinity semantics (see scheduling/topology.py module docstring), with a
reason per pod. The benchmark asserts the solver's actual drops equal the
oracle's expectation (``unexplained == 0``); tests pin the classification.

Reasons:

- ``anti-affinity-zone-exhausted``: required zonal anti-affinity where the
  selector-matching members outnumber the zones they may claim. With Z clean
  zones (no existing cluster match) the group can place at most Z matching
  members — or Z-1 when non-matching members also exist, since those need one
  zone kept free of matchers. Any schedule violating that drops MORE pods.
- ``anti-affinity-no-clean-zone``: every viable zone already holds a
  cluster pod matching the anti-affinity selector, so no member can land.
- ``affinity-no-provider``: required pod affinity whose selector matches no
  batch pod and no scheduled cluster pod — nothing to co-locate with.
- ``no-instance-type-fits``: the pod's resource requests exceed every
  instance type's usable (allocatable minus overhead) capacity.
- ``pod-zone-pin-unsatisfiable``: an anti-affinity member whose own
  nodeSelector/affinity narrows the zone to something no viable zone offers.
"""

from __future__ import annotations

from typing import Dict, List, Sequence

import numpy as np

from karpenter_tpu.api import labels as lbl
from karpenter_tpu.api.objects import Pod
from karpenter_tpu.api.provisioner import Constraints
from karpenter_tpu.cloudprovider.types import InstanceType
from karpenter_tpu.kube.client import Cluster
from karpenter_tpu.scheduling.topology import DomainPlan, Topology, ignored_for_topology
from karpenter_tpu.utils import resources as res

ANTI_ZONE_EXHAUSTED = "anti-affinity-zone-exhausted"
ANTI_NO_CLEAN_ZONE = "anti-affinity-no-clean-zone"
AFFINITY_NO_PROVIDER = "affinity-no-provider"
NO_CAPACITY = "no-instance-type-fits"
PIN_NO_VIABLE_ZONE = "pod-zone-pin-unsatisfiable"


def expected_unschedulable(
    cluster: Cluster,
    constraints: Constraints,
    instance_types: Sequence[InstanceType],
    pods: Sequence[Pod],
):
    """The drops any schedule must incur.

    Returns ``(exact, budgets)``: ``exact`` maps pod.key → reason for pods
    that are individually impossible; each budget is
    ``{"reason", "candidates" (keys), "count"}`` for constraint classes
    where exactly ``count`` pods out of ``candidates`` must drop but WHICH
    ones is the scheduler's free choice (e.g. which excess anti-affinity
    matchers — the solver drops the smallest after its FFD sort)."""
    exact: Dict[str, str] = {}
    budgets: List[Dict[str, object]] = []
    topo = Topology(cluster)
    batch = list(pods)
    # the oracle reasons about PRE-injection state: an empty plan
    plan = DomainPlan(batch)
    viable = constraints.requirements.zones()

    for group in topo._affinity_groups(batch):
        if group.key == lbl.TOPOLOGY_ZONE and group.anti:
            topo._count_cluster_matches(group)
            clean = [d for d in viable if group.match_counts.get(d, 0) == 0]
            # a member whose own narrowing excludes every viable zone is
            # individually impossible and doesn't consume group capacity
            members = []
            for p in group.pods:
                if topo._allowed_domains(p, group.key, viable, plan):
                    members.append(p)
                else:
                    exact[p.key] = PIN_NO_VIABLE_ZONE
            matching = [p for p in members if group.selector_matches(p)]
            nonmatching = [p for p in members if not group.selector_matches(p)]
            if not clean:
                for p in members:
                    exact[p.key] = ANTI_NO_CLEAN_ZONE
                continue
            # capacity for matchers: one per clean zone, minus the zone
            # reserved for non-matching members (who need zero matchers) —
            # reserved only when some non-matcher can actually use a clean
            # zone, mirroring the injection (topology.py)
            reserve = bool(matching) and any(
                topo._allowed_domains(p, group.key, set(clean), plan)
                for p in nonmatching
            )
            capacity = len(clean) - (1 if reserve else 0)
            excess = len(matching) - max(capacity, 0)
            if excess > 0:
                budgets.append(
                    {
                        "reason": ANTI_ZONE_EXHAUSTED,
                        "candidates": {p.key for p in matching},
                        "count": excess,
                    }
                )
        elif not group.anti:
            # a provider can come from the batch, or — for zonal affinity
            # only — from scheduled cluster pods (hostname affinity targets
            # a fresh node, so only batch pods can provide the match:
            # topology.py _assign_hostname_affinity)
            provider, _ = Topology._batch_provider(group, batch, plan)
            if provider is not None:
                continue
            if group.key == lbl.TOPOLOGY_ZONE and _cluster_has_match(cluster, group):
                continue
            for p in group.pods:
                exact[p.key] = AFFINITY_NO_PROVIDER

    # resource feasibility: request vector must fit SOME instance type's
    # usable capacity (allocatable minus overhead) — same axis discovery and
    # capacity math as the encoder (solver/encode.py)
    from karpenter_tpu.solver.encode import usable_capacity

    axes = res.collect_extra_axes(
        [it.resources for it in instance_types]
        + [it.overhead for it in instance_types]
        + [res.requests_for_pods(p) for p in batch]
    )
    usable = usable_capacity(instance_types, axes)
    for p in batch:
        if p.key in exact:
            continue
        req = res.to_scaled_vector(res.requests_for_pods(p), axes)
        if not bool((usable >= req).all(axis=1).any()):
            exact[p.key] = NO_CAPACITY
    return exact, budgets


def _cluster_has_match(cluster: Cluster, group) -> bool:
    for namespace in group.namespaces():
        for p in cluster.list_pods_matching(namespace, group.term.label_selector):
            if not ignored_for_topology(p):
                return True
    return False


def classify_drops(
    cluster: Cluster,
    constraints: Constraints,
    instance_types: Sequence[InstanceType],
    pods: Sequence[Pod],
    scheduled: Sequence[Pod],
) -> Dict[str, object]:
    """Compare a solve's actual drops against the oracle's expectation.

    Returns ``{"dropped": N, "expected": {reason: count}, "unexplained": [...],
    "missed": [...]}`` where ``unexplained`` lists dropped pods the oracle
    cannot justify (scheduler artifact) and ``missed`` lists pods the oracle
    deems impossible yet the solver placed (oracle/model divergence)."""
    placed = {id(p) for p in scheduled}
    dropped = [p for p in pods if id(p) not in placed]
    exact, budgets = expected_unschedulable(cluster, constraints, instance_types, pods)
    dropped_keys = {p.key for p in dropped}
    counts: Dict[str, int] = {}
    explained: set = set()
    missed: List[str] = []
    for key in dropped_keys:
        reason = exact.get(key)
        if reason is not None:
            counts[reason] = counts.get(reason, 0) + 1
            explained.add(key)
    missed += [k for k in exact if k not in dropped_keys]
    for budget in budgets:
        hit = sorted(dropped_keys & budget["candidates"])  # type: ignore[operator]
        reason, count = str(budget["reason"]), int(budget["count"])  # type: ignore[arg-type]
        if hit:
            counts[reason] = counts.get(reason, 0) + min(len(hit), count)
        explained.update(hit[:count])
        if len(hit) < count:
            # the solver placed more than the proven capacity — the model
            # (or the solver) is wrong; surface it
            missed.append(f"{reason}: {count - len(hit)} under budget")
    return {
        "dropped": len(dropped),
        "expected": counts,
        "unexplained": sorted(k for k in dropped_keys if k not in explained),
        "missed": missed,
    }
