"""Topology-spread handling by pre-assignment.

Mirrors ``pkg/controllers/provisioning/scheduling/topology.go`` +
``topologygroup.go``: pods are grouped by equivalent (namespace, constraint);
existing matching pods are counted per domain from the live cluster (zones:
viable zones from requirements; hostnames: ``ceil(len(pods)/maxSkew)`` fresh
generated names); then each pod gets the current min-count domain written into
its nodeSelector, turning TopologySpreadConstraints into just-in-time
NodeSelectors the packing core understands natively.
"""

from __future__ import annotations

import math
import random
import string
from typing import Dict, List, Optional, Set, Tuple

from karpenter_tpu.api import labels as lbl
from karpenter_tpu.api.objects import Pod, TopologySpreadConstraint
from karpenter_tpu.api.provisioner import Constraints
from karpenter_tpu.api.requirements import Requirements
from karpenter_tpu.api.objects import NodeSelectorRequirement
from karpenter_tpu.kube.client import Cluster
from karpenter_tpu.utils import pod as podutil


class TopologyGroup:
    """Pods sharing one topology spread constraint, with per-domain skew
    counts (reference: topologygroup.go:22-68)."""

    def __init__(self, pod: Pod, constraint: TopologySpreadConstraint):
        self.constraint = constraint
        self.pods: List[Pod] = [pod]
        self.spread: Dict[str, int] = {}

    def register(self, *domains: str) -> None:
        for d in domains:
            self.spread[d] = 0

    def increment(self, domain: str) -> None:
        if domain in self.spread:
            self.spread[domain] += 1

    def next_domain(self, allowed: Set[str]) -> str:
        """Argmin over allowed registered domains; ties broken toward the
        later-iterated key like the reference's `<=` comparison."""
        min_domain = ""
        min_count = None
        for domain, count in self.spread.items():
            if domain not in allowed:
                continue
            if min_count is None or count <= min_count:
                min_domain = domain
                min_count = count
        self.spread[min_domain] = self.spread.get(min_domain, 0) + 1
        return min_domain


def _group_key(namespace: str, c: TopologySpreadConstraint) -> Tuple:
    sel = c.label_selector
    sel_key: Tuple = ()
    if sel is not None:
        sel_key = (
            tuple(sorted(sel.match_labels.items())),
            tuple((e.key, e.operator, tuple(e.values)) for e in sel.match_expressions),
        )
    return (namespace, c.max_skew, c.topology_key, c.when_unsatisfiable, sel_key)


class Topology:
    def __init__(self, cluster: Cluster, rng: Optional[random.Random] = None):
        self.cluster = cluster
        self.rng = rng or random.Random()

    def inject(self, constraints: Constraints, pods: List[Pod]) -> None:
        """Write a topology-chosen domain into each pod's nodeSelector
        (reference: topology.go:41-57). Mutates pods and, for hostname
        spread, the constraints' requirements."""
        for group in self._topology_groups(pods):
            self._compute_current_topology(constraints, group)
            for pod in group.pods:
                allowed_set = (
                    constraints.requirements.merge(Requirements.from_pod(pod))
                    .get(group.constraint.topology_key)
                )
                # Hostname domains were layered into constraints; zone domains
                # come from the viable-zone registration. Either way the pod's
                # own requirements may narrow them.
                allowed = {d for d in group.spread if allowed_set.has(d)}
                domain = group.next_domain(allowed)
                pod.spec.node_selector = {**pod.spec.node_selector, group.constraint.topology_key: domain}

    def _topology_groups(self, pods: List[Pod]) -> List[TopologyGroup]:
        groups: Dict[Tuple, TopologyGroup] = {}
        for pod in pods:
            for constraint in pod.spec.topology_spread_constraints:
                key = _group_key(pod.metadata.namespace, constraint)
                if key in groups:
                    groups[key].pods.append(pod)
                else:
                    groups[key] = TopologyGroup(pod, constraint)
        return list(groups.values())

    def _compute_current_topology(self, constraints: Constraints, group: TopologyGroup) -> None:
        key = group.constraint.topology_key
        if key == lbl.HOSTNAME:
            self._compute_hostname_topology(group, constraints)
        elif key == lbl.TOPOLOGY_ZONE:
            self._compute_zonal_topology(constraints, group)

    def _compute_hostname_topology(self, group: TopologyGroup, constraints: Constraints) -> None:
        """Fresh nodes are empty, so the global hostname minimum is 0; we
        generate ceil(n/maxSkew) domains so skew cannot be violated
        (reference: topology.go:98-112)."""
        n_domains = math.ceil(len(group.pods) / max(group.constraint.max_skew, 1))
        domains = [
            "".join(self.rng.choices(string.ascii_lowercase + string.digits, k=8))
            for _ in range(n_domains)
        ]
        group.register(*domains)
        constraints.requirements = constraints.requirements.add(
            NodeSelectorRequirement(key=lbl.HOSTNAME, operator="In", values=domains)
        )

    def _compute_zonal_topology(self, constraints: Constraints, group: TopologyGroup) -> None:
        """Viable zones become the domains; existing matching cluster pods
        seed the skew counts (reference: topology.go:119-127)."""
        group.register(*constraints.requirements.zones())
        self._count_matching_pods(group)

    def _count_matching_pods(self, group: TopologyGroup) -> None:
        namespace = group.pods[0].metadata.namespace
        for p in self.cluster.list_pods_matching(namespace, group.constraint.label_selector):
            if ignored_for_topology(p):
                continue
            node = self.cluster.try_get("nodes", p.spec.node_name, namespace="")
            if node is None:
                continue
            domain = node.metadata.labels.get(group.constraint.topology_key)
            if domain is not None:
                group.increment(domain)


def ignored_for_topology(p: Pod) -> bool:
    return not podutil.is_scheduled(p) or podutil.is_terminal(p) or podutil.is_terminating(p)
