"""Topology handling by pre-assignment: spread constraints AND pod
(anti-)affinity.

Spread mirrors ``pkg/controllers/provisioning/scheduling/topology.go`` +
``topologygroup.go``: pods are grouped by equivalent (namespace, constraint);
existing matching pods are counted per domain from the live cluster (zones:
viable zones from requirements; hostnames: ``ceil(len(pods)/maxSkew)`` fresh
generated names); then each pod gets the current min-count domain assigned,
turning TopologySpreadConstraints into just-in-time NodeSelectors the packing
core understands natively.

Pod affinity/anti-affinity is NEW capability (BASELINE config 3; the
reference rejects it at selection, selection/controller.go:145-150, with its
intended semantics sketched by the skipped suite contexts,
scheduling/suite_test.go:1014-1080). The same pre-assignment trick applies —
pairwise pod×pod×domain constraints become per-pod domain decisions made
sequentially against membership counters:

- affinity(S, zone):    land in a zone already containing a pod matching S
                        (cluster counts seed the table); a self-matching or
                        batch-provided group with no existing matches gets a
                        single seed zone so it co-locates with itself.
- affinity(S, host):    the group shares one fresh hostname — one node.
- anti(S, zone):        land in a zone with zero matches; each placed pod
                        that matches S claims its zone.
- anti(S, host):        pods matching S get one fresh hostname each (pairwise
                        separation); non-matching pods share a separate fresh
                        hostname away from the providers.

Pods with unsatisfiable rules get a sentinel domain no node can offer, so the
packer counts and logs them unschedulable instead of mis-placing them.

Decisions are recorded in a ``DomainPlan`` — NOT written into the pods'
nodeSelectors. The TPU encode consumes the plan directly (zero pod mutation
on the hot path); the FFD packer calls ``plan.materialize`` to get the
classic just-in-time NodeSelector form, so affinity support lands in both
backends from the same decision logic.
"""

from __future__ import annotations

import math
import random
from typing import Dict, List, Optional, Set, Tuple

from karpenter_tpu.api import labels as lbl
from karpenter_tpu.api.objects import (
    NodeSelectorRequirement,
    Pod,
    PodAffinityTerm,
    TopologySpreadConstraint,
)
from karpenter_tpu.api.provisioner import Constraints
from karpenter_tpu.kube.client import Cluster
from karpenter_tpu.scheduling.statics import (
    SUPPORTED_AFFINITY_KEYS as SUPPORTED_AFFINITY_KEYS_STATICS,
    PodStatics,
    satisfies,
    statics,
)
from karpenter_tpu.utils import pod as podutil

# A domain no catalog offers: forces "no instance type satisfied" for pods
# whose affinity rules cannot be met, keeping them visibly unschedulable.
UNSATISFIABLE_DOMAIN = "unsatisfiable.karpenter.sh"

# re-exported from statics (the grouping pass that enforces it lives there)
SUPPORTED_AFFINITY_KEYS = SUPPORTED_AFFINITY_KEYS_STATICS


class DomainPlan:
    """Per-pod injected topology decisions, keyed by pod identity.

    Reads fall back to the pod's own (raw) nodeSelector, so plan-aware code
    sees exactly the view the old selector-mutation flow produced, without
    touching the pods. ``materialize`` applies the decisions as selector
    overlays for the FFD path (callers snapshot/restore around it)."""

    __slots__ = ("ztokens", "hostdecs", "_pods", "sts")

    # canonical NON-hostname decision tuples, interned PROCESS-WIDE so the
    # encode can memo per (pod template, decisions) on object identity
    # across solves — hostname decisions are excluded because the canonical
    # core never contains the hostname key (the kernel carries it as an int
    # field). Clear-safe: live plans keep their canonical objects alive.
    _tok_intern: Dict[Tuple, Tuple] = {}

    def __init__(self, pods: List[Pod]):
        # THE storage: ztokens maps pod id -> interned sorted tuple of the
        # pod's non-hostname decisions; hostdecs maps pod id -> injected
        # hostname. Decisions per pod are 1-2 items, so the tuple IS the
        # map — no per-pod dict allocation on the hot path, and the encode
        # loop reads both with one plain dict get each.
        self.ztokens: Dict[int, Tuple] = {}
        self.hostdecs: Dict[int, Optional[str]] = {}
        self._pods = pods  # keeps ids stable for the plan's lifetime
        self.sts: Optional[List] = None  # statics parallel to `pods`, set by inject_plan

    def decision(self, pod: Pod, key: str) -> Optional[str]:
        pid = id(pod)
        if key == lbl.HOSTNAME:
            return self.hostdecs.get(pid)
        tok = self.ztokens.get(pid)
        if tok:
            for k, v in tok:
                if k == key:
                    return v
        return None

    def get(self, pod: Pod, key: str) -> Optional[str]:
        v = self.decision(pod, key)
        return v if v is not None else pod.spec.node_selector.get(key)

    def set(self, pod: Pod, key: str, domain: str) -> None:
        pid = id(pod)
        if key == lbl.HOSTNAME:
            self.hostdecs[pid] = domain
            return
        tok = self.ztokens.get(pid)
        if not tok:
            self.ztokens[pid] = self.intern_token(key, domain)
            return
        merged = dict(tok)
        merged[key] = domain
        self.ztokens[pid] = self._intern(tuple(sorted(merged.items())))

    @staticmethod
    def _intern(items: Tuple) -> Tuple:
        intern = DomainPlan._tok_intern
        if len(intern) > (1 << 20):
            intern.clear()
        return intern.setdefault(items, items)

    def zone_token(self, pod: Pod) -> Tuple:
        """Canonical interned tuple of this pod's non-hostname decisions."""
        return self.ztokens.get(id(pod), ())

    @staticmethod
    def intern_token(key: str, domain: str) -> Tuple:
        """The canonical interned token of a single zone-class decision —
        lets bulk writers stamp one shared token across a whole group."""
        return DomainPlan._intern(((key, domain),))

    def set_zone_bulk(self, members, key: str, domain: str) -> None:
        """Assign one non-hostname decision to many pods at once, stamping
        the shared interned token. Pods that already carry a different
        non-hostname decision merge through the generic ``set`` path."""
        tok = self.intern_token(key, domain)
        ztokens = self.ztokens
        ztokens_get = ztokens.get
        for pod in members:
            pid = id(pod)
            old = ztokens_get(pid)
            if not old or (len(old) == 1 and old[0][0] == key):
                ztokens[pid] = tok
            else:
                self.set(pod, key, domain)

    def set_hostname_bulk(self, pods_and_names) -> None:
        """Assign hostname decisions for many (pod, name) pairs; hostname
        never contributes to zone tokens, so no token bookkeeping."""
        self.hostdecs.update((id(pod), name) for pod, name in pods_and_names)

    def items(self, pod: Pod) -> Optional[Dict[str, str]]:
        """This pod's decisions as a dict (fresh object), or None."""
        pid = id(pod)
        tok = self.ztokens.get(pid)
        host = self.hostdecs.get(pid)
        if not tok and host is None:
            return None
        d = dict(tok) if tok else {}
        if host is not None:
            d[lbl.HOSTNAME] = host
        return d

    def materialize(self, pods: List[Pod]) -> None:
        """Write decisions into the pods' nodeSelectors (always replacing
        the dict, never mutating in place, so snapshot/restore works)."""
        for p in pods:
            d = self.items(p)
            if d:
                p.spec.node_selector = {**p.spec.node_selector, **d}


class TopologyGroup:
    """Pods sharing one topology spread constraint, with per-domain skew
    counts (reference: topologygroup.go:22-68)."""

    def __init__(self, pod: Pod, constraint: TopologySpreadConstraint):
        self.constraint = constraint
        self.pods: List[Pod] = [pod]
        self.sts: List[PodStatics] = []
        self.spread: Dict[str, int] = {}

    def register(self, *domains: str) -> None:
        for d in domains:
            self.spread[d] = 0

    def increment(self, domain: str) -> None:
        if domain in self.spread:
            self.spread[domain] += 1

    def next_domain(self, allowed: Optional[Set[str]]) -> str:
        """Argmin over allowed registered domains (``None`` = all of them,
        no membership test); ties broken toward the later-iterated key like
        the reference's `<=` comparison."""
        min_domain = ""
        min_count = None
        for domain, count in self.spread.items():
            if allowed is not None and domain not in allowed:
                continue
            if min_count is None or count <= min_count:
                min_domain = domain
                min_count = count
        self.spread[min_domain] = self.spread.get(min_domain, 0) + 1
        return min_domain


class AffinityGroup:
    """Pods sharing one required pod (anti-)affinity term."""

    def __init__(self, namespace: str, term: PodAffinityTerm, anti: bool):
        self.namespace = namespace
        self.term = term
        self.anti = anti
        self.pods: List[Pod] = []
        self.sts: List[PodStatics] = []  # parallel to pods
        # domain -> number of pods matching the term's selector there
        self.match_counts: Dict[str, int] = {}
        self._namespaces = (
            set(term.namespaces) if term.namespaces else {namespace}
        )
        self._match_memo: Dict[Tuple, bool] = {}

    @property
    def key(self) -> str:
        return self.term.topology_key

    def match_flags(self, members) -> List[bool]:
        """``selector_matches`` over (pod, statics) pairs with the memo and
        namespace test hoisted — this runs O(pods) per group per solve."""
        sel = self.term.label_selector
        nss = self._namespaces
        if sel is None:
            return [p.metadata.namespace in nss for p, _ in members]
        memo = self._match_memo
        out = []
        append = out.append
        matches = sel.matches
        for pod, st in members:
            if pod.metadata.namespace not in nss:
                append(False)
                continue
            lk = st.labels_key
            hit = memo.get(lk)
            if hit is None:
                hit = memo[lk] = matches(pod.metadata.labels)
            append(hit)
        return out

    def selector_matches(self, pod: Pod, st: Optional[PodStatics] = None) -> bool:
        if pod.metadata.namespace not in self._namespaces:
            return False
        sel = self.term.label_selector
        if sel is None:
            return True
        # memoized by label set: a group's pods share few distinct label
        # maps, and this runs O(pods × groups) per solve
        lk = (st or statics(pod)).labels_key
        hit = self._match_memo.get(lk)
        if hit is None:
            hit = self._match_memo[lk] = sel.matches(pod.metadata.labels)
        return hit

    def namespaces(self) -> Set[str]:
        return self._namespaces


class Topology:
    def __init__(self, cluster: Cluster, rng: Optional[random.Random] = None):
        self.cluster = cluster
        self.rng = rng or random.Random()

    # -- public ------------------------------------------------------------
    def inject(self, constraints: Constraints, pods: List[Pod]) -> DomainPlan:
        """Legacy mutating form: compute the plan, then write each pod's
        chosen domains into its nodeSelector (reference: topology.go:41-57).
        Callers snapshot/restore selectors around solves."""
        plan = self.inject_plan(constraints, pods)
        plan.materialize(pods)
        return plan

    def inject_plan(
        self,
        constraints: Constraints,
        pods: List[Pod],
        sts: Optional[List[PodStatics]] = None,
    ) -> DomainPlan:
        """Compute a topology decision per pod WITHOUT mutating the pods.
        Affinity first — its choices narrow what spread sees — then host
        ports, then spread. Hostname domains are registered into the
        constraints' requirements. ``sts`` lets the caller share one
        statics pass across sort → inject → encode."""
        plan = DomainPlan(pods)
        if sts is None:
            sts = [statics(p) for p in pods]  # ONE statics pass for the solve
        plan.sts = sts
        generated_hostnames: List[str] = []
        # ONE discovery pass distributes pods into all three phase
        # structures (three separate 10k-pod scans were a third of inject)
        aff_groups: Dict[Tuple, AffinityGroup] = {}
        spread_groups: Dict[Tuple, TopologyGroup] = {}
        port_members: List[Tuple[Pod, PodStatics]] = []
        self._discover(pods, sts, aff_groups, spread_groups, port_members)
        self._inject_affinity(
            constraints, pods, list(aff_groups.values()), generated_hostnames, plan
        )
        self._inject_host_ports(port_members, generated_hostnames, plan)
        self._inject_spread(
            constraints, list(spread_groups.values()), generated_hostnames, plan
        )
        if generated_hostnames:
            # one registration for the union: per-group adds would intersect
            # per-key sets and empty the hostname domain
            constraints.requirements = constraints.requirements.add(
                NodeSelectorRequirement(
                    key=lbl.HOSTNAME, operator="In", values=generated_hostnames
                )
            )
        return plan

    # -- discovery ---------------------------------------------------------
    @staticmethod
    def _discover(pods, sts, aff_groups, spread_groups, port_members) -> None:
        """Distribute pods into affinity/spread/port structures. Large
        batches are bucketed by the statics-interned topology-class code and
        gathered with numpy — one C-level gather per (class, group) instead
        of 10k Python-level appends — preserving batch order within every
        group (stable argsort). Registry-overflow pods (code -1) ride the
        same bucketed pass as singleton entries at their batch positions so
        member order matches the per-pod (<512) path exactly."""
        n = len(pods)
        if n >= 512:
            import operator

            import numpy as np

            codes = np.fromiter(
                map(operator.attrgetter("topo_code"), sts), np.int64, count=n
            )
            if codes.any():
                order = np.argsort(codes, kind="stable")
                sorted_codes = codes[order]
                uniq, starts = np.unique(sorted_codes, return_index=True)
                bounds = list(starts.tolist()) + [n]
                # visit classes in order of FIRST APPEARANCE in the batch,
                # not registry-code order: group creation order decides
                # processing order downstream (stable anti-first sort), and
                # it must match the per-pod path / be independent of what
                # earlier solves registered
                first_pos = order[starts].tolist()
                aff_idx: Dict[Tuple, list] = {}
                spread_idx: Dict[Tuple, list] = {}
                port_idx: list = []
                # Registry-overflow pods (code -1) join the visit as
                # singleton entries at their own batch positions instead of
                # a trailing per-pod pass: once the class registry fills,
                # member order — which drives zone/hostname assignment —
                # must stay batch-interleaved exactly like the per-pod
                # (<512) path (ADVICE r4).
                entries: list = []
                for j in range(len(uniq)):
                    code = int(uniq[j])
                    if code == 0:
                        continue
                    idx = order[bounds[j]:bounds[j + 1]]
                    if code == -1:
                        entries.extend(
                            (int(i), idx[k:k + 1]) for k, i in enumerate(idx)
                        )
                    else:
                        entries.append((first_pos[j], idx))
                entries.sort(key=operator.itemgetter(0))
                for _, idx in entries:
                    rep = sts[int(idx[0])]
                    for key, term, anti in rep.aff_terms:
                        if key not in aff_groups:
                            aff_groups[key] = AffinityGroup(
                                pods[int(idx[0])].metadata.namespace, term, anti
                            )
                        aff_idx.setdefault(key, []).append(idx)
                    for key, constraint in rep.spreads:
                        if key not in spread_groups:
                            g = spread_groups[key] = TopologyGroup(
                                pods[int(idx[0])], constraint
                            )
                            g.pods.pop()  # ctor added the pod; gathered below
                        spread_idx.setdefault(key, []).append(idx)
                    if rep.host_ports:
                        port_idx.append(idx)

                def gather(target_pods, target_sts, idx_arrays):
                    idx = (
                        np.sort(np.concatenate(idx_arrays))
                        if len(idx_arrays) > 1
                        else idx_arrays[0]
                    ).tolist()
                    getter = operator.itemgetter(*idx)
                    if len(idx) == 1:
                        target_pods.append(getter(pods))
                        target_sts.append(getter(sts))
                    else:
                        target_pods.extend(getter(pods))
                        target_sts.extend(getter(sts))

                for key, arrays in aff_idx.items():
                    g = aff_groups[key]
                    gather(g.pods, g.sts, arrays)
                for key, arrays in spread_idx.items():
                    g = spread_groups[key]
                    gather(g.pods, g.sts, arrays)
                if port_idx:
                    idx = (
                        np.sort(np.concatenate(port_idx))
                        if len(port_idx) > 1
                        else port_idx[0]
                    ).tolist()
                    port_members.extend((pods[i], sts[i]) for i in idx)
                return
            return  # no pod in the batch has topology features
        # small batch: per-pod path
        aff_get = aff_groups.get
        spread_get = spread_groups.get
        for pod, st in zip(pods, sts):
            if not st.topo_any:
                continue
            if st.aff_terms:
                for key, term, anti in st.aff_terms:
                    g = aff_get(key)
                    if g is None:
                        g = aff_groups[key] = AffinityGroup(
                            pod.metadata.namespace, term, anti
                        )
                    g.pods.append(pod)
                    g.sts.append(st)
            if st.host_ports:
                port_members.append((pod, st))
            if st.spreads:
                for key, constraint in st.spreads:
                    g = spread_get(key)
                    if g is None:
                        g = spread_groups[key] = TopologyGroup(pod, constraint)
                        g.pods.pop()  # ctor added the pod; re-add with its st
                    g.pods.append(pod)
                    g.sts.append(st)

    # -- pod (anti-)affinity ----------------------------------------------
    def _inject_affinity(
        self,
        constraints: Constraints,
        pods: List[Pod],
        groups: List[AffinityGroup],
        generated_hostnames: List[str],
        plan: DomainPlan,
    ) -> None:
        if not groups:
            return
        batch = list(pods)
        # anti-affinity first: it is the more constrained rule (needs empty
        # domains), and affinity groups can then adopt whatever domains the
        # anti pass pinned instead of greedily seeding a conflicting one
        groups.sort(key=lambda g: not g.anti)
        for group in groups:
            if group.key == lbl.TOPOLOGY_ZONE:
                self._assign_zonal_affinity(constraints, group, batch, plan)
            elif group.key == lbl.HOSTNAME:
                self._assign_hostname_affinity(group, batch, generated_hostnames, plan)

    def _affinity_groups(
        self, pods: List[Pod], sts: Optional[List[PodStatics]] = None
    ) -> List[AffinityGroup]:
        if sts is None:
            sts = [statics(p) for p in pods]
        groups: Dict[Tuple, AffinityGroup] = {}
        for pod, st in zip(pods, sts):
            for key, term, anti in st.aff_terms:
                group = groups.get(key)
                if group is None:
                    group = groups[key] = AffinityGroup(pod.metadata.namespace, term, anti)
                group.pods.append(pod)
                group.sts.append(st)
        return list(groups.values())

    def _count_cluster_matches(self, group: AffinityGroup) -> None:
        """Seed match counts from scheduled cluster pods, keyed by their
        node's topology domain."""
        for namespace in group.namespaces():
            for p in self.cluster.list_pods_matching(namespace, group.term.label_selector):
                if ignored_for_topology(p):
                    continue
                node = self.cluster.try_get("nodes", p.spec.node_name, namespace="")
                if node is None:
                    continue
                domain = node.metadata.labels.get(group.key)
                if domain is not None:
                    group.match_counts[domain] = group.match_counts.get(domain, 0) + 1

    @staticmethod
    def _narrowed(
        st: PodStatics, pin: Optional[str], key: str, domains: Set[str]
    ) -> Optional[Set[str]]:
        """The subset of ``domains`` this pod may take — or ``None`` meaning
        "all of them" (the overwhelmingly common case, returned without
        copying the domain set). ``pin`` is a domain an earlier injection
        pass already chose (the plan-aware form of re-reading the mutated
        selector); ``domains`` is already constraint-viable, so only the
        pod's OWN narrowing needs checking."""
        entries = st.key_entries.get(key)
        if pin is None and not entries:
            return None
        out = set()
        for d in domains:
            if pin is not None and d != pin:
                continue
            if entries and not satisfies(entries, d):
                continue
            out.add(d)
        return out

    @staticmethod
    def _allowed_domains(
        pod: Pod, key: str, domains: Set[str], plan: DomainPlan
    ) -> Set[str]:
        """Compat form of ``_narrowed`` returning a real set (oracle and
        slow paths)."""
        out = Topology._narrowed(
            statics(pod), plan.decision(pod, key), key, domains
        )
        return set(domains) if out is None else out

    def _assign_zonal_affinity(
        self,
        constraints: Constraints,
        group: AffinityGroup,
        batch: List[Pod],
        plan: DomainPlan,
    ) -> None:
        self._count_cluster_matches(group)
        viable = constraints.requirements.zones()
        key = group.key
        members = list(zip(group.pods, group.sts))
        # bulk fast path: no member is narrowed by its own spec and none is
        # pinned by an earlier pass — the per-pod loops then degenerate to a
        # handful of distinct domains stamped across the whole group (the
        # overwhelmingly common shape: template pods with pod-affinity only)
        unrestricted = _group_unrestricted(key, group.pods, group.sts, plan)
        if unrestricted and group.anti:
            flags = group.match_flags(members)
            n_match = sum(flags)
            clean = sorted(d for d in viable if group.match_counts.get(d, 0) == 0)
            # one clean zone is reserved for the non-matching cohort (see the
            # general path below for the rationale); with no narrowing the
            # reservation choice is simply the first clean zone
            reserved = clean[0] if (n_match and n_match < len(flags) and clean) else None
            free_list = [d for d in clean if d != reserved]
            matching_pods = [p for (p, _), m in zip(members, flags) if m]
            # matchers claim one free zone each; beyond the free zones they
            # are provably unplaceable
            placed = matching_pods[: len(free_list)]
            for d, pod in zip(free_list, placed):
                group.match_counts[d] = 1
                plan.set_zone_bulk((pod,), key, d)
            if len(matching_pods) > len(placed):
                plan.set_zone_bulk(matching_pods[len(placed):], key, UNSATISFIABLE_DOMAIN)
            if n_match < len(flags):
                free_nm = sorted(
                    d for d in viable if group.match_counts.get(d, 0) == 0
                )
                shared_nm = free_nm[0] if free_nm else UNSATISFIABLE_DOMAIN
                plan.set_zone_bulk(
                    [p for (p, _), m in zip(members, flags) if not m], key, shared_nm
                )
            return
        if unrestricted and not group.anti and members:
            # resolve the FIRST member through the general logic (it may
            # seed a domain via a batch provider); every later unrestricted
            # member then picks the populated argmax, which placing there
            # only strengthens — so the rest of the group lands on one
            # domain computed once
            self._assign_zonal_affinity_general(
                constraints, group, batch, plan, [members[0]], viable, key
            )
            rest = members[1:]
            if not rest:
                return
            populated = sorted(
                (d for d in viable if group.match_counts.get(d, 0) > 0),
                key=lambda d: (-group.match_counts[d], d),
            )
            if populated:
                # match_counts is not updated for the bulk members: the
                # group is complete after this write and nothing reads the
                # counts afterwards (cross-group state flows via plan pins)
                plan.set_zone_bulk([p for p, _ in rest], key, populated[0])
            else:
                # first member resolved unsatisfiable with no counts: no
                # provider exists for the whole group
                plan.set_zone_bulk([p for p, _ in rest], key, UNSATISFIABLE_DOMAIN)
            return
        self._assign_zonal_affinity_general(
            constraints, group, batch, plan, members, viable, key
        )

    def _assign_zonal_affinity_general(
        self,
        constraints: Constraints,
        group: AffinityGroup,
        batch: List[Pod],
        plan: DomainPlan,
        members,
        viable,
        key: str,
        pins=None,
    ) -> None:
        if pins is None:
            pins = [plan.decision(p, key) for p, _ in members]
        if group.anti:
            # Selector-matching members claim a zone each (pairwise
            # separation); non-matching members only need SOME zone free of
            # matchers. Placing a matcher in every clean zone would strand
            # the whole non-matching cohort — trading one matcher for N
            # non-matchers is never a win — so one clean zone is reserved
            # for them. This keeps drops to the provable minimum:
            # max(m - (clean - 1), 0) matchers (see scheduling/oracle.py).
            flags = group.match_flags(members)
            matching = [
                (p, st, pin)
                for ((p, st), pin), m in zip(zip(members, pins), flags)
                if m
            ]
            nonmatching = [
                (p, st, pin)
                for ((p, st), pin), m in zip(zip(members, pins), flags)
                if not m
            ]
            reserved: Optional[str] = None
            if nonmatching and matching:
                clean = sorted(
                    d for d in viable if group.match_counts.get(d, 0) == 0
                )
                # reserve the clean zone usable by the most non-matchers;
                # break ties toward the zone the fewest matchers are pinned
                # to — reserving a matcher's only allowed zone would drop a
                # placeable matcher
                matcher_allowed = [
                    self._narrowed(st, pin, key, viable)
                    for _, st, pin in matching
                ]
                best = None
                for d in clean:
                    n_ok = sum(
                        1
                        for _, st, pin in nonmatching
                        if self._narrowed(st, pin, key, {d}) in (None, {d})
                    )
                    m_only = sum(1 for a in matcher_allowed if a == {d})
                    if n_ok and (best is None or (n_ok, -m_only) > (best[0], -best[1])):
                        best = (n_ok, m_only, d)
                if best is not None:
                    reserved = best[2]
            # amortized claim: unrestricted matchers take zones off one
            # shared sorted free list instead of re-sorting per pod
            free_list = sorted(
                d for d in viable
                if group.match_counts.get(d, 0) == 0 and d != reserved
            )
            for pod, st, pin in matching:
                allowed = self._narrowed(st, pin, key, viable)
                if allowed is None:
                    domain = free_list[0] if free_list else UNSATISFIABLE_DOMAIN
                else:
                    free = sorted(
                        d
                        for d in allowed
                        if group.match_counts.get(d, 0) == 0 and d != reserved
                    )
                    domain = free[0] if free else UNSATISFIABLE_DOMAIN
                plan.set(pod, key, domain)
                if domain != UNSATISFIABLE_DOMAIN:
                    group.match_counts[domain] = group.match_counts.get(domain, 0) + 1
                    if free_list and free_list[0] == domain:
                        free_list.pop(0)
                    elif domain in free_list:
                        free_list.remove(domain)
            # non-matchers never increment counts, so they all resolve to
            # the same first free zone — computed once for the unrestricted
            free_nm = sorted(d for d in viable if group.match_counts.get(d, 0) == 0)
            shared_nm = free_nm[0] if free_nm else UNSATISFIABLE_DOMAIN
            for pod, st, pin in nonmatching:
                allowed = self._narrowed(st, pin, key, viable)
                if allowed is None:
                    domain = shared_nm
                else:
                    free = sorted(d for d in allowed if group.match_counts.get(d, 0) == 0)
                    domain = free[0] if free else UNSATISFIABLE_DOMAIN
                plan.set(pod, key, domain)
            return
        # affinity: most-populated existing domain, else a seed the group
        # itself (or a batch provider) will populate. The argmax is
        # recomputed only when the counts' argmax can change (a provider
        # seed or a first placement), not per pod.
        populated_domain: Optional[str] = None
        populated_dirty = True
        for pod, st in members:
            # the pin must be read LIVE, not from the pre-loop snapshot: a
            # provider seeded earlier in THIS loop (plan.set below) must see
            # its own pin when its iteration comes, or it gets re-assigned
            # away from the consumer that adopted it
            pin = plan.decision(pod, key)
            allowed = self._narrowed(st, pin, key, viable)
            if populated_dirty:
                populated = sorted(
                    (d for d in viable if group.match_counts.get(d, 0) > 0),
                    key=lambda d: (-group.match_counts[d], d),
                )
                populated_domain = populated[0] if populated else None
                populated_dirty = False
            if allowed is None and populated_domain is not None:
                # placing here only strengthens the argmax — no recompute
                domain = populated_domain
            elif allowed is not None and any(
                group.match_counts.get(d, 0) > 0 for d in allowed
            ):
                # narrowed pod: argmax over ITS allowed populated domains
                acceptable = sorted(
                    (d for d in allowed if group.match_counts.get(d, 0) > 0),
                    key=lambda d: (-group.match_counts[d], d),
                )
                domain = acceptable[0]
            else:
                provider, pinned = self._batch_provider(group, batch, plan)
                if provider is None or (allowed is not None and not allowed):
                    domain = UNSATISFIABLE_DOMAIN
                elif pinned is not None:
                    # adopt the provider's already-pinned domain if this pod
                    # may go there; else unsatisfiable
                    domain = (
                        pinned
                        if (allowed is None or pinned in allowed) and pinned in viable
                        else UNSATISFIABLE_DOMAIN
                    )
                else:
                    # seed a domain BOTH the consumer and the provider may
                    # use — pinning the provider outside its own node
                    # affinity would render it unschedulable
                    provider_allowed = self._allowed_domains(
                        provider, key, viable, plan
                    )
                    joint = sorted(
                        (viable if allowed is None else allowed) & provider_allowed
                    )
                    domain = joint[0] if joint else UNSATISFIABLE_DOMAIN
                if domain != UNSATISFIABLE_DOMAIN and provider is not pod:
                    # ensure the provider actually lands there
                    plan.set(provider, key, domain)
                    if group.selector_matches(provider):
                        group.match_counts[domain] = group.match_counts.get(domain, 0) + 1
                        populated_dirty = True
            plan.set(pod, key, domain)
            if domain != UNSATISFIABLE_DOMAIN and group.selector_matches(pod, st):
                group.match_counts[domain] = group.match_counts.get(domain, 0) + 1
                if domain != populated_domain:
                    populated_dirty = True

    def _assign_hostname_affinity(
        self,
        group: AffinityGroup,
        batch: List[Pod],
        generated_hostnames: List[str],
        plan: DomainPlan,
    ) -> None:
        if group.anti:
            # pairwise separation: a fresh node per selector-matching
            # member; non-matchers only avoid the providers and share one.
            # Names are drawn in one batched rng call.
            flags = group.match_flags(list(zip(group.pods, group.sts)))
            n_match = sum(flags)
            fresh = self._fresh_hostnames(
                n_match + (1 if n_match < len(flags) else 0), generated_hostnames
            )
            shared_for_nonmatching = fresh[n_match] if n_match < len(flags) else None
            it = iter(fresh)
            plan.set_hostname_bulk(
                (pod, next(it) if matched else shared_for_nonmatching)
                for pod, matched in zip(group.pods, flags)
            )
            return
        # affinity: the whole group lands on one fresh node, provided the
        # match can come from the group itself or another batch pod
        provider, pinned = self._batch_provider(group, batch, plan)
        if provider is None:
            for pod in group.pods:
                _mark_unschedulable(pod, plan)
            return
        shared = pinned if pinned is not None else self._fresh_hostname(generated_hostnames)
        plan.set(provider, group.key, shared)
        plan.set_hostname_bulk((pod, shared) for pod in group.pods)

    @staticmethod
    def _batch_provider(
        group: AffinityGroup, batch: List[Pod], plan: DomainPlan
    ) -> Tuple[Optional[Pod], Optional[str]]:
        """A batch pod that satisfies the group's selector — preferring group
        members (self-affinity), then unpinned batch pods, then batch pods
        already pinned to a domain (returned so the group can adopt it)."""
        pinned_candidate: Optional[Pod] = None
        for pod in group.pods:
            if group.selector_matches(pod):
                return pod, plan.get(pod, group.key)
        for pod in batch:
            if not group.selector_matches(pod):
                continue
            pinned = plan.get(pod, group.key)
            if pinned is None:
                return pod, None
            if pinned_candidate is None:
                pinned_candidate = pod
        if pinned_candidate is not None:
            return pinned_candidate, plan.get(pinned_candidate, group.key)
        return None, None

    def _fresh_hostname(self, generated_hostnames: List[str]) -> str:
        # 40 random bits as hex text: same entropy class as the old 8-char
        # alphanumeric draw at ~1/4 the cost (a host-spread batch generates
        # thousands of these per solve)
        name = f"h{self.rng.getrandbits(40):010x}"
        generated_hostnames.append(name)
        return name

    def _fresh_hostnames(self, n: int, generated_hostnames: List[str]) -> List[str]:
        """n fresh hostnames from ONE rng draw (one 40n-bit integer sliced
        into 10-hex-char chunks) — per-call rng overhead dominated the
        anti-affinity hostname loops at thousands of names per solve."""
        if n <= 0:
            return []
        blob = f"{self.rng.getrandbits(40 * n):0{10 * n}x}"
        names = [f"h{blob[10 * k:10 * (k + 1)]}" for k in range(n)]
        generated_hostnames.extend(names)
        return names

    # -- host ports --------------------------------------------------------
    def _inject_host_ports(
        self,
        port_members: List[Tuple[Pod, PodStatics]],
        generated_hostnames: List[str],
        plan: DomainPlan,
    ) -> None:
        """Host-port claims are per-node mutable state the tensor encoding
        does not carry, so they become hostname pre-assignments like
        anti-affinity: port-claiming pods are bucketed onto fresh hostnames
        such that no bucket holds conflicting claims; pods whose other
        selectors differ never share a bucket (a merged bucket must stay
        jointly feasible). Pods already hostname-pinned (by affinity) keep
        their pin; a conflict inside one pin is unsatisfiable."""
        buckets: List[Tuple[str, set, Tuple]] = []  # (hostname, claims, selector key)
        pinned_claims: Dict[str, set] = {}
        for pod, st in port_members:
            claims = st.host_ports
            pinned = _pinned_hostname(pod, plan, st)
            if pinned is not None:
                existing = pinned_claims.setdefault(pinned, set())
                if podutil.host_ports_conflict(claims, existing):
                    _mark_unschedulable(pod, plan)
                else:
                    existing |= claims
                continue
            dec = plan.items(pod)
            selector_key = tuple(
                sorted(({**dict(st.sel_raw), **dec} if dec else dict(st.sel_raw)).items())
            )
            placed = False
            for hostname, bucket_claims, bucket_key in buckets:
                if bucket_key != selector_key:
                    continue
                if podutil.host_ports_conflict(claims, bucket_claims):
                    continue
                bucket_claims |= claims
                plan.set(pod, lbl.HOSTNAME, hostname)
                placed = True
                break
            if not placed:
                hostname = self._fresh_hostname(generated_hostnames)
                buckets.append((hostname, set(claims), selector_key))
                plan.set(pod, lbl.HOSTNAME, hostname)

    # -- topology spread ---------------------------------------------------
    def _inject_spread(
        self,
        constraints: Constraints,
        groups: List[TopologyGroup],
        generated_hostnames: List[str],
        plan: DomainPlan,
    ) -> None:
        # hostname-spread groups draw their fresh domains from one shared
        # pool: spread only constrains skew WITHIN a group, so different
        # groups may deliberately overlap on the same hostnames and the
        # packer co-locates them when resources allow — materially fewer
        # nodes than private per-group domains. Affinity/anti-affinity/port
        # hostnames stay private (a spread pod could match their selectors).
        hostname_pool: List[str] = []
        for group in groups:
            self._compute_current_topology(
                constraints, group, generated_hostnames, hostname_pool, plan
            )
            key = group.constraint.topology_key
            if key == lbl.HOSTNAME and not any(
                _pod_constrains(p, lbl.HOSTNAME, plan, st)
                for p, st in zip(group.pods, group.sts)
            ):
                # fast path: all-fresh domains, zero seed counts, no pinned
                # pods → min-count assignment degenerates to round-robin
                # (the general path is O(pods × domains) = O(n²/maxSkew)).
                # Inlined plan writes: hostname decisions never touch zone
                # tokens, and this loop runs for thousands of pods per solve
                domains = list(group.spread)  # pool order → cross-group overlap
                n_dom = len(domains)
                n_mem = len(group.pods)
                assigned = [domains[j % n_dom] for j in range(n_mem)]
                plan.hostdecs.update(zip(map(id, group.pods), assigned))
                for j in range(min(n_dom, n_mem)):
                    # members j, j+n_dom, j+2*n_dom, ... landed on domains[j]
                    group.spread[domains[j]] += (n_mem - j + n_dom - 1) // n_dom
                continue
            registered = group.spread.keys()
            soft = group.constraint.when_unsatisfiable == "ScheduleAnyway"
            narrowed = self._narrowed
            decision = plan.decision
            next_domain = group.next_domain
            is_hostname = key == lbl.HOSTNAME
            ztokens = plan.ztokens
            hostdecs = plan.hostdecs
            if not is_hostname and registered:
                # bulk fast path: no member narrowed by its own spec and
                # none pinned by an earlier pass — the per-pod argmin over
                # counts (ties toward the later-iterated key, matching
                # next_domain's <=) becomes a tight water-filling sim with
                # one bulk write per domain
                if _group_unrestricted(key, group.pods, group.sts, plan):
                    doms = list(registered)
                    counts = [group.spread[d] for d in doms]
                    nd = len(doms)
                    by_dom: List[List[Pod]] = [[] for _ in range(nd)]
                    for pod in group.pods:
                        m_i = 0
                        m_c = counts[0]
                        for j in range(1, nd):
                            if counts[j] <= m_c:
                                m_i = j
                                m_c = counts[j]
                        counts[m_i] += 1
                        by_dom[m_i].append(pod)
                    for j, members in enumerate(by_dom):
                        group.spread[doms[j]] = counts[j]
                        if members:
                            plan.set_zone_bulk(members, key, doms[j])
                    continue
            tok_cache: Dict[str, Tuple] = {}
            for pod, st in zip(group.pods, group.sts):
                # the pod's own requirements may narrow the registered
                # domains; registered domains are already constraint-viable
                allowed = narrowed(st, decision(pod, key), key, registered)
                if is_hostname:
                    pinned = plan.get(pod, lbl.HOSTNAME)
                    if pinned is not None:
                        allowed = (
                            {pinned}
                            if allowed is None
                            else (allowed & {pinned})
                        )
                if allowed is not None and not allowed:
                    # the pod's own narrowing excludes every registered
                    # domain. ScheduleAnyway is a SOFT constraint
                    # (reference: 'should violate max-skew when unsat =
                    # schedule anyway'): leave the pod unconstrained by this
                    # spread and let it schedule. DoNotSchedule falls
                    # through to next_domain's empty pick ("" — no offering
                    # provides it), keeping the pod visibly unschedulable.
                    if soft:
                        continue
                domain = next_domain(allowed)
                # inlined plan.set with eager token stamping: zone-spread
                # batches run this for thousands of pods per solve
                pid = id(pod)
                if is_hostname:
                    hostdecs[pid] = domain
                    continue
                old = ztokens.get(pid)
                if not old or (len(old) == 1 and old[0][0] == key):
                    tok = tok_cache.get(domain)
                    if tok is None:
                        tok = tok_cache[domain] = DomainPlan.intern_token(key, domain)
                    ztokens[pid] = tok
                else:
                    plan.set(pod, key, domain)

    def _topology_groups(
        self, pods: List[Pod], sts: Optional[List[PodStatics]] = None
    ) -> List[TopologyGroup]:
        if sts is None:
            sts = [statics(p) for p in pods]
        groups: Dict[Tuple, TopologyGroup] = {}
        for pod, st in zip(pods, sts):
            for key, constraint in st.spreads:
                g = groups.get(key)
                if g is None:
                    g = groups[key] = TopologyGroup(pod, constraint)
                    g.pods.pop()  # ctor added the pod; re-add with its st
                g.pods.append(pod)
                g.sts.append(st)
        return list(groups.values())

    def _compute_current_topology(
        self,
        constraints: Constraints,
        group: TopologyGroup,
        generated_hostnames: List[str],
        hostname_pool: List[str],
        plan: DomainPlan,
    ) -> None:
        key = group.constraint.topology_key
        if key == lbl.HOSTNAME:
            self._compute_hostname_topology(group, generated_hostnames, hostname_pool, plan)
        elif key == lbl.TOPOLOGY_ZONE:
            self._compute_zonal_topology(constraints, group)

    def _compute_hostname_topology(
        self,
        group: TopologyGroup,
        generated_hostnames: List[str],
        hostname_pool: List[str],
        plan: DomainPlan,
    ) -> None:
        """Fresh nodes are empty, so the global hostname minimum is 0; we
        register ceil(n/maxSkew) domains — drawn from the shared pool so
        groups overlap — and skew cannot be violated
        (reference: topology.go:98-112)."""
        n_domains = math.ceil(len(group.pods) / max(group.constraint.max_skew, 1))
        if len(hostname_pool) < n_domains:
            hostname_pool.extend(
                self._fresh_hostnames(
                    n_domains - len(hostname_pool), generated_hostnames
                )
            )
        # pods already pinned to a hostname by affinity participate with that
        # hostname as a registered domain
        for pod in group.pods:
            pinned = plan.get(pod, lbl.HOSTNAME)
            if pinned is not None:
                group.register(pinned)
        group.register(*hostname_pool[:n_domains])

    def _compute_zonal_topology(self, constraints: Constraints, group: TopologyGroup) -> None:
        """Viable zones become the domains; existing matching cluster pods
        seed the skew counts (reference: topology.go:119-127)."""
        group.register(*constraints.requirements.zones())
        self._count_matching_pods(group)

    def _count_matching_pods(self, group: TopologyGroup) -> None:
        namespace = group.pods[0].metadata.namespace
        for p in self.cluster.list_pods_matching(namespace, group.constraint.label_selector):
            if ignored_for_topology(p):
                continue
            node = self.cluster.try_get("nodes", p.spec.node_name, namespace="")
            if node is None:
                continue
            domain = node.metadata.labels.get(group.constraint.topology_key)
            if domain is not None:
                group.increment(domain)


def snapshot_selectors(pods: List[Pod]) -> List[Dict[str, str]]:
    """The pods' nodeSelector dicts before materialization. Materialization
    always replaces the dict (never mutates in place), so restoring the
    original references undoes every injected decision — solving must not
    leave stale domain pins on live pod objects (a retried pod would drag
    its previous round's hostname/zone into the next solve)."""
    return [p.spec.node_selector for p in pods]


def restore_selectors(pods: List[Pod], saved: List[Dict[str, str]]) -> None:
    for p, s in zip(pods, saved):
        p.spec.node_selector = s


def _group_unrestricted(key: str, pods, sts, plan: DomainPlan) -> bool:
    """The bulk fast paths' shared gate: no member's own spec narrows
    ``key`` and no member carries a prior injected decision on it. MUST
    stay in sync with ``_narrowed``'s inputs — key_entries plus the
    plan's non-hostname decisions (zone tokens)."""
    if any(key in st.key_entries for st in sts):
        return False
    ztokens_get = plan.ztokens.get
    return not any(
        (tok := ztokens_get(id(p))) and any(k == key for k, _ in tok)
        for p in pods
    )


def _pinned_hostname(
    pod: Pod, plan: DomainPlan, st: Optional[PodStatics] = None
) -> Optional[str]:
    """The hostname the pod is already pinned to — by an injected decision,
    its own nodeSelector, or its own required node affinity."""
    pinned = plan.get(pod, lbl.HOSTNAME)
    if pinned is not None:
        return pinned
    return (st or statics(pod)).pinned_aff_hostname


def _pod_constrains(
    pod: Pod, key: str, plan: DomainPlan, st: Optional[PodStatics] = None
) -> bool:
    """Does the pod's own spec — or an earlier injection pass — narrow this
    topology key? Cheap pre-check gating the spread fast path."""
    if plan.decision(pod, key) is not None:
        return True
    return key in (st or statics(pod)).constrains


def _mark_unschedulable(pod: Pod, plan: DomainPlan) -> None:
    """Pin the pod to a zone no offering can provide: zone feasibility is
    enforced by the instance-type offering filter for every catalog, unlike
    hostname, so this reliably drops (and logs) the pod at pack time."""
    plan.set(pod, lbl.TOPOLOGY_ZONE, UNSATISFIABLE_DOMAIN)


def ignored_for_topology(p: Pod) -> bool:
    return not podutil.is_scheduled(p) or podutil.is_terminal(p) or podutil.is_terminating(p)
