"""Topology handling by pre-assignment: spread constraints AND pod
(anti-)affinity.

Spread mirrors ``pkg/controllers/provisioning/scheduling/topology.go`` +
``topologygroup.go``: pods are grouped by equivalent (namespace, constraint);
existing matching pods are counted per domain from the live cluster (zones:
viable zones from requirements; hostnames: ``ceil(len(pods)/maxSkew)`` fresh
generated names); then each pod gets the current min-count domain written into
its nodeSelector, turning TopologySpreadConstraints into just-in-time
NodeSelectors the packing core understands natively.

Pod affinity/anti-affinity is NEW capability (BASELINE config 3; the
reference rejects it at selection, selection/controller.go:145-150, with its
intended semantics sketched by the skipped suite contexts,
scheduling/suite_test.go:1014-1080). The same pre-assignment trick applies —
pairwise pod×pod×domain constraints become per-pod domain decisions made
sequentially against membership counters:

- affinity(S, zone):    land in a zone already containing a pod matching S
                        (cluster counts seed the table); a self-matching or
                        batch-provided group with no existing matches gets a
                        single seed zone so it co-locates with itself.
- affinity(S, host):    the group shares one fresh hostname — one node.
- anti(S, zone):        land in a zone with zero matches; each placed pod
                        that matches S claims its zone.
- anti(S, host):        pods matching S get one fresh hostname each (pairwise
                        separation); non-matching pods share a separate fresh
                        hostname away from the providers.

Pods with unsatisfiable rules get a sentinel domain no node can offer, so the
packer counts and logs them unschedulable instead of mis-placing them.

Because both backends consume the injected NodeSelectors, affinity support
lands in the FFD packer and the TPU batch solver simultaneously.
"""

from __future__ import annotations

import math
import random
import string
from typing import Dict, List, Optional, Set, Tuple

from karpenter_tpu.api import labels as lbl
from karpenter_tpu.api.objects import (
    LabelSelector,
    NodeSelectorRequirement,
    Pod,
    PodAffinityTerm,
    TopologySpreadConstraint,
)
from karpenter_tpu.api.provisioner import Constraints
from karpenter_tpu.api.requirements import Requirements
from karpenter_tpu.kube.client import Cluster
from karpenter_tpu.utils import pod as podutil

# A domain no catalog offers: forces "no instance type satisfied" for pods
# whose affinity rules cannot be met, keeping them visibly unschedulable.
UNSATISFIABLE_DOMAIN = "unsatisfiable.karpenter.sh"

SUPPORTED_AFFINITY_KEYS = (lbl.HOSTNAME, lbl.TOPOLOGY_ZONE)


class TopologyGroup:
    """Pods sharing one topology spread constraint, with per-domain skew
    counts (reference: topologygroup.go:22-68)."""

    def __init__(self, pod: Pod, constraint: TopologySpreadConstraint):
        self.constraint = constraint
        self.pods: List[Pod] = [pod]
        self.spread: Dict[str, int] = {}

    def register(self, *domains: str) -> None:
        for d in domains:
            self.spread[d] = 0

    def increment(self, domain: str) -> None:
        if domain in self.spread:
            self.spread[domain] += 1

    def next_domain(self, allowed: Set[str]) -> str:
        """Argmin over allowed registered domains; ties broken toward the
        later-iterated key like the reference's `<=` comparison."""
        min_domain = ""
        min_count = None
        for domain, count in self.spread.items():
            if domain not in allowed:
                continue
            if min_count is None or count <= min_count:
                min_domain = domain
                min_count = count
        self.spread[min_domain] = self.spread.get(min_domain, 0) + 1
        return min_domain


class AffinityGroup:
    """Pods sharing one required pod (anti-)affinity term."""

    def __init__(self, namespace: str, term: PodAffinityTerm, anti: bool):
        self.namespace = namespace
        self.term = term
        self.anti = anti
        self.pods: List[Pod] = []
        # domain -> number of pods matching the term's selector there
        self.match_counts: Dict[str, int] = {}

    @property
    def key(self) -> str:
        return self.term.topology_key

    def selector_matches(self, pod: Pod) -> bool:
        if pod.metadata.namespace not in self.namespaces():
            return False
        sel = self.term.label_selector
        return sel is None or sel.matches(pod.metadata.labels)

    def namespaces(self) -> Set[str]:
        return set(self.term.namespaces) if self.term.namespaces else {self.namespace}


def _selector_key(sel: Optional[LabelSelector]) -> Tuple:
    if sel is None:
        return ()
    # memoized on the selector object — selectors are immutable in practice
    # and this runs per pod per solve
    cached = getattr(sel, "_canon_key", None)
    if cached is not None:
        return cached
    key = (
        tuple(sorted(sel.match_labels.items())),
        tuple((e.key, e.operator, tuple(e.values)) for e in sel.match_expressions),
    )
    try:
        sel._canon_key = key
    except AttributeError:
        pass
    return key


def _group_key(namespace: str, c: TopologySpreadConstraint) -> Tuple:
    return (namespace, c.max_skew, c.topology_key, c.when_unsatisfiable,
            _selector_key(c.label_selector))


def _affinity_key(namespace: str, term: PodAffinityTerm, anti: bool) -> Tuple:
    ns = tuple(sorted(term.namespaces)) if term.namespaces else (namespace,)
    return (anti, ns, term.topology_key, _selector_key(term.label_selector))


def snapshot_selectors(pods: List[Pod]) -> List[Dict[str, str]]:
    """The pods' nodeSelector dicts before injection. Injection always
    replaces the dict (never mutates in place), so restoring the original
    references undoes every injected decision — solving must not leave
    stale domain pins on live pod objects (a retried pod would drag its
    previous round's hostname/zone into the next solve)."""
    return [p.spec.node_selector for p in pods]


def restore_selectors(pods: List[Pod], saved: List[Dict[str, str]]) -> None:
    for p, s in zip(pods, saved):
        p.spec.node_selector = s


class Topology:
    def __init__(self, cluster: Cluster, rng: Optional[random.Random] = None):
        self.cluster = cluster
        self.rng = rng or random.Random()

    # -- public ------------------------------------------------------------
    def inject(self, constraints: Constraints, pods: List[Pod]) -> None:
        """Write a topology-chosen domain into each pod's nodeSelector
        (reference: topology.go:41-57). Affinity first — its choices narrow
        what spread sees — then spread. Mutates pods and, for hostname
        domains, the constraints' requirements."""
        generated_hostnames: List[str] = []
        self._inject_affinity(constraints, pods, generated_hostnames)
        self._inject_host_ports(pods, generated_hostnames)
        self._inject_spread(constraints, pods, generated_hostnames)
        if generated_hostnames:
            # one registration for the union: per-group adds would intersect
            # per-key sets and empty the hostname domain
            constraints.requirements = constraints.requirements.add(
                NodeSelectorRequirement(
                    key=lbl.HOSTNAME, operator="In", values=generated_hostnames
                )
            )

    # -- pod (anti-)affinity ----------------------------------------------
    def _inject_affinity(
        self,
        constraints: Constraints,
        pods: List[Pod],
        generated_hostnames: List[str],
    ) -> None:
        groups = self._affinity_groups(pods)
        if not groups:
            return
        batch = list(pods)
        # anti-affinity first: it is the more constrained rule (needs empty
        # domains), and affinity groups can then adopt whatever domains the
        # anti pass pinned instead of greedily seeding a conflicting one
        groups.sort(key=lambda g: not g.anti)
        for group in groups:
            if group.key == lbl.TOPOLOGY_ZONE:
                self._assign_zonal_affinity(constraints, group, batch)
            elif group.key == lbl.HOSTNAME:
                self._assign_hostname_affinity(group, batch, generated_hostnames)

    def _affinity_groups(self, pods: List[Pod]) -> List[AffinityGroup]:
        groups: Dict[Tuple, AffinityGroup] = {}
        for pod in pods:
            aff = pod.spec.affinity
            if aff is None:
                continue
            terms: List[Tuple[PodAffinityTerm, bool]] = []
            if aff.pod_affinity is not None:
                terms += [(t, False) for t in aff.pod_affinity.required]
            if aff.pod_anti_affinity is not None:
                terms += [(t, True) for t in aff.pod_anti_affinity.required]
            for term, anti in terms:
                if term.topology_key not in SUPPORTED_AFFINITY_KEYS:
                    continue
                key = _affinity_key(pod.metadata.namespace, term, anti)
                group = groups.get(key)
                if group is None:
                    group = groups[key] = AffinityGroup(pod.metadata.namespace, term, anti)
                group.pods.append(pod)
        return list(groups.values())

    def _count_cluster_matches(self, group: AffinityGroup) -> None:
        """Seed match counts from scheduled cluster pods, keyed by their
        node's topology domain."""
        for namespace in group.namespaces():
            for p in self.cluster.list_pods_matching(namespace, group.term.label_selector):
                if ignored_for_topology(p):
                    continue
                node = self.cluster.try_get("nodes", p.spec.node_name, namespace="")
                if node is None:
                    continue
                domain = node.metadata.labels.get(group.key)
                if domain is not None:
                    group.match_counts[domain] = group.match_counts.get(domain, 0) + 1

    def _allowed_domains(
        self, constraints: Constraints, pod: Pod, key: str, domains: Set[str]
    ) -> Set[str]:
        """``domains`` is already constraint-viable, so only the pod's OWN
        narrowing needs checking — merging the pod into the full (catalog-
        sized) constraint requirements per pod made injection O(n·|catalog|)."""
        # fast path: a pod with no selector and no node affinity narrows
        # nothing — building its Requirements per call dominated injection
        # at 10k pods (most benchmark pods are unconstrained)
        if not pod.spec.node_selector and (
            pod.spec.affinity is None or pod.spec.affinity.node_affinity is None
        ):
            return set(domains)
        pod_reqs = Requirements.from_pod(pod)
        if not pod_reqs.has(key):
            return set(domains)
        pod_set = pod_reqs.get(key)
        return {d for d in domains if pod_set.has(d)}

    def _assign_zonal_affinity(
        self, constraints: Constraints, group: AffinityGroup, batch: List[Pod]
    ) -> None:
        self._count_cluster_matches(group)
        viable = constraints.requirements.zones()
        if group.anti:
            # Selector-matching members claim a zone each (pairwise
            # separation); non-matching members only need SOME zone free of
            # matchers. Placing a matcher in every clean zone would strand
            # the whole non-matching cohort — trading one matcher for N
            # non-matchers is never a win — so one clean zone is reserved
            # for them. This keeps drops to the provable minimum:
            # max(m - (clean - 1), 0) matchers (see scheduling/oracle.py).
            matching = [p for p in group.pods if group.selector_matches(p)]
            nonmatching = [p for p in group.pods if not group.selector_matches(p)]
            reserved: Optional[str] = None
            if nonmatching and matching:
                clean = sorted(
                    d for d in viable if group.match_counts.get(d, 0) == 0
                )
                # reserve the clean zone usable by the most non-matchers;
                # break ties toward the zone the fewest matchers are pinned
                # to — reserving a matcher's only allowed zone would drop a
                # placeable matcher
                matcher_allowed = [
                    self._allowed_domains(constraints, p, group.key, viable)
                    for p in matching
                ]
                best = None
                for d in clean:
                    n_ok = sum(
                        1
                        for p in nonmatching
                        if d in self._allowed_domains(constraints, p, group.key, {d})
                    )
                    m_only = sum(1 for a in matcher_allowed if a == {d})
                    if n_ok and (best is None or (n_ok, -m_only) > (best[0], -best[1])):
                        best = (n_ok, m_only, d)
                if best is not None:
                    reserved = best[2]
            for pod in matching:
                allowed = self._allowed_domains(constraints, pod, group.key, viable)
                free = sorted(
                    d
                    for d in allowed
                    if group.match_counts.get(d, 0) == 0 and d != reserved
                )
                domain = free[0] if free else UNSATISFIABLE_DOMAIN
                _set_domain(pod, group.key, domain)
                if domain != UNSATISFIABLE_DOMAIN:
                    group.match_counts[domain] = group.match_counts.get(domain, 0) + 1
            for pod in nonmatching:
                allowed = self._allowed_domains(constraints, pod, group.key, viable)
                free = sorted(d for d in allowed if group.match_counts.get(d, 0) == 0)
                domain = free[0] if free else UNSATISFIABLE_DOMAIN
                _set_domain(pod, group.key, domain)
            return
        # affinity: most-populated existing domain, else a seed the group
        # itself (or a batch provider) will populate
        for pod in group.pods:
            allowed = self._allowed_domains(constraints, pod, group.key, viable)
            populated = sorted(
                (d for d in allowed if group.match_counts.get(d, 0) > 0),
                key=lambda d: (-group.match_counts[d], d),
            )
            if populated:
                domain = populated[0]
            else:
                provider, pinned = self._batch_provider(group, batch)
                if provider is None or not allowed:
                    domain = UNSATISFIABLE_DOMAIN
                elif pinned is not None:
                    # adopt the provider's already-pinned domain if this pod
                    # may go there; else unsatisfiable
                    domain = pinned if pinned in allowed else UNSATISFIABLE_DOMAIN
                else:
                    # seed a domain BOTH the consumer and the provider may
                    # use — pinning the provider outside its own node
                    # affinity would render it unschedulable
                    provider_allowed = self._allowed_domains(
                        constraints, provider, group.key, viable
                    )
                    joint = sorted(allowed & provider_allowed)
                    domain = joint[0] if joint else UNSATISFIABLE_DOMAIN
                if domain != UNSATISFIABLE_DOMAIN and provider is not pod:
                    # ensure the provider actually lands there
                    _set_domain(provider, group.key, domain)
                    if group.selector_matches(provider):
                        group.match_counts[domain] = group.match_counts.get(domain, 0) + 1
            _set_domain(pod, group.key, domain)
            if domain != UNSATISFIABLE_DOMAIN and group.selector_matches(pod):
                group.match_counts[domain] = group.match_counts.get(domain, 0) + 1

    def _assign_hostname_affinity(
        self, group: AffinityGroup, batch: List[Pod], generated_hostnames: List[str]
    ) -> None:
        if group.anti:
            shared_for_nonmatching: Optional[str] = None
            for pod in group.pods:
                if group.selector_matches(pod):
                    # pairwise separation: a fresh node each
                    domain = self._fresh_hostname(generated_hostnames)
                else:
                    # must only avoid the providers' nodes; share one
                    if shared_for_nonmatching is None:
                        shared_for_nonmatching = self._fresh_hostname(generated_hostnames)
                    domain = shared_for_nonmatching
                _set_domain(pod, group.key, domain)
            return
        # affinity: the whole group lands on one fresh node, provided the
        # match can come from the group itself or another batch pod
        provider, pinned = self._batch_provider(group, batch)
        if provider is None:
            for pod in group.pods:
                _mark_unschedulable(pod)
            return
        shared = pinned if pinned is not None else self._fresh_hostname(generated_hostnames)
        _set_domain(provider, group.key, shared)
        for pod in group.pods:
            _set_domain(pod, group.key, shared)

    @staticmethod
    def _batch_provider(
        group: AffinityGroup, batch: List[Pod]
    ) -> Tuple[Optional[Pod], Optional[str]]:
        """A batch pod that satisfies the group's selector — preferring group
        members (self-affinity), then unpinned batch pods, then batch pods
        already pinned to a domain (returned so the group can adopt it)."""
        pinned_candidate: Optional[Pod] = None
        for pod in group.pods:
            if group.selector_matches(pod):
                return pod, pod.spec.node_selector.get(group.key)
        for pod in batch:
            if not group.selector_matches(pod):
                continue
            if group.key not in pod.spec.node_selector:
                return pod, None
            if pinned_candidate is None:
                pinned_candidate = pod
        if pinned_candidate is not None:
            return pinned_candidate, pinned_candidate.spec.node_selector[group.key]
        return None, None

    def _fresh_hostname(self, generated_hostnames: List[str]) -> str:
        name = "".join(self.rng.choices(string.ascii_lowercase + string.digits, k=8))
        generated_hostnames.append(name)
        return name

    # -- host ports --------------------------------------------------------
    def _inject_host_ports(self, pods: List[Pod], generated_hostnames: List[str]) -> None:
        """Host-port claims are per-node mutable state the tensor encoding
        does not carry, so they become hostname pre-assignments like
        anti-affinity: port-claiming pods are bucketed onto fresh hostnames
        such that no bucket holds conflicting claims; pods whose other
        selectors differ never share a bucket (a merged bucket must stay
        jointly feasible). Pods already hostname-pinned (by affinity) keep
        their pin; a conflict inside one pin is unsatisfiable."""
        buckets: List[Tuple[str, set, Tuple]] = []  # (hostname, claims, selector key)
        pinned_claims: Dict[str, set] = {}
        for pod in pods:
            claims = podutil.host_ports(pod)
            if not claims:
                continue
            pinned = _pinned_hostname(pod)
            if pinned is not None:
                existing = pinned_claims.setdefault(pinned, set())
                if podutil.host_ports_conflict(claims, existing):
                    _mark_unschedulable(pod)
                else:
                    existing |= claims
                continue
            selector_key = tuple(sorted(pod.spec.node_selector.items()))
            placed = False
            for hostname, bucket_claims, bucket_key in buckets:
                if bucket_key != selector_key:
                    continue
                if podutil.host_ports_conflict(claims, bucket_claims):
                    continue
                bucket_claims |= claims
                _set_domain(pod, lbl.HOSTNAME, hostname)
                placed = True
                break
            if not placed:
                hostname = self._fresh_hostname(generated_hostnames)
                buckets.append((hostname, set(claims), selector_key))
                _set_domain(pod, lbl.HOSTNAME, hostname)

    # -- topology spread ---------------------------------------------------
    def _inject_spread(
        self,
        constraints: Constraints,
        pods: List[Pod],
        generated_hostnames: List[str],
    ) -> None:
        # hostname-spread groups draw their fresh domains from one shared
        # pool: spread only constrains skew WITHIN a group, so different
        # groups may deliberately overlap on the same hostnames and the
        # packer co-locates them when resources allow — materially fewer
        # nodes than private per-group domains. Affinity/anti-affinity/port
        # hostnames stay private (a spread pod could match their selectors).
        hostname_pool: List[str] = []
        for group in self._topology_groups(pods):
            self._compute_current_topology(constraints, group, generated_hostnames, hostname_pool)
            key = group.constraint.topology_key
            if key == lbl.HOSTNAME and not any(
                _pod_constrains(p, lbl.HOSTNAME) for p in group.pods
            ):
                # fast path: all-fresh domains, zero seed counts, no pinned
                # pods → min-count assignment degenerates to round-robin
                # (the general path is O(pods × domains) = O(n²/maxSkew))
                domains = list(group.spread)  # pool order → cross-group overlap
                for j, pod in enumerate(group.pods):
                    domain = domains[j % len(domains)]
                    group.spread[domain] += 1
                    _set_domain(pod, key, domain)
                continue
            for pod in group.pods:
                # the pod's own requirements may narrow the registered
                # domains; registered domains are already constraint-viable
                allowed = self._allowed_domains(constraints, pod, key, set(group.spread))
                if key == lbl.HOSTNAME:
                    pinned = pod.spec.node_selector.get(lbl.HOSTNAME)
                    if pinned is not None:
                        allowed &= {pinned}
                domain = group.next_domain(allowed)
                _set_domain(pod, key, domain)

    def _topology_groups(self, pods: List[Pod]) -> List[TopologyGroup]:
        groups: Dict[Tuple, TopologyGroup] = {}
        for pod in pods:
            for constraint in pod.spec.topology_spread_constraints:
                key = _group_key(pod.metadata.namespace, constraint)
                if key in groups:
                    groups[key].pods.append(pod)
                else:
                    groups[key] = TopologyGroup(pod, constraint)
        return list(groups.values())

    def _compute_current_topology(
        self,
        constraints: Constraints,
        group: TopologyGroup,
        generated_hostnames: List[str],
        hostname_pool: List[str],
    ) -> None:
        key = group.constraint.topology_key
        if key == lbl.HOSTNAME:
            self._compute_hostname_topology(group, generated_hostnames, hostname_pool)
        elif key == lbl.TOPOLOGY_ZONE:
            self._compute_zonal_topology(constraints, group)

    def _compute_hostname_topology(
        self,
        group: TopologyGroup,
        generated_hostnames: List[str],
        hostname_pool: List[str],
    ) -> None:
        """Fresh nodes are empty, so the global hostname minimum is 0; we
        register ceil(n/maxSkew) domains — drawn from the shared pool so
        groups overlap — and skew cannot be violated
        (reference: topology.go:98-112)."""
        n_domains = math.ceil(len(group.pods) / max(group.constraint.max_skew, 1))
        while len(hostname_pool) < n_domains:
            hostname_pool.append(self._fresh_hostname(generated_hostnames))
        # pods already pinned to a hostname by affinity participate with that
        # hostname as a registered domain
        for pod in group.pods:
            pinned = pod.spec.node_selector.get(lbl.HOSTNAME)
            if pinned is not None:
                group.register(pinned)
        group.register(*hostname_pool[:n_domains])

    def _compute_zonal_topology(self, constraints: Constraints, group: TopologyGroup) -> None:
        """Viable zones become the domains; existing matching cluster pods
        seed the skew counts (reference: topology.go:119-127)."""
        group.register(*constraints.requirements.zones())
        self._count_matching_pods(group)

    def _count_matching_pods(self, group: TopologyGroup) -> None:
        namespace = group.pods[0].metadata.namespace
        for p in self.cluster.list_pods_matching(namespace, group.constraint.label_selector):
            if ignored_for_topology(p):
                continue
            node = self.cluster.try_get("nodes", p.spec.node_name, namespace="")
            if node is None:
                continue
            domain = node.metadata.labels.get(group.constraint.topology_key)
            if domain is not None:
                group.increment(domain)


def _set_domain(pod: Pod, key: str, domain: str) -> None:
    pod.spec.node_selector = {**pod.spec.node_selector, key: domain}


def _pinned_hostname(pod: Pod) -> Optional[str]:
    """The hostname the pod is already pinned to — by nodeSelector (domain
    injection writes there) or by its own required node affinity."""
    pinned = pod.spec.node_selector.get(lbl.HOSTNAME)
    if pinned is not None:
        return pinned
    aff = pod.spec.affinity
    if aff is None or aff.node_affinity is None:
        return None
    for term in aff.node_affinity.required:
        for r in term.match_expressions:
            if r.key == lbl.HOSTNAME and r.operator == "In" and len(r.values) == 1:
                return r.values[0]
    return None


def _pod_constrains(pod: Pod, key: str) -> bool:
    """Does the pod's own spec narrow this topology key (selector or node
    affinity)? Cheap pre-check gating the spread fast path."""
    if key in pod.spec.node_selector:
        return True
    aff = pod.spec.affinity
    if aff is None or aff.node_affinity is None:
        return False
    for term in aff.node_affinity.required:
        if any(r.key == key for r in term.match_expressions):
            return True
    for pref in aff.node_affinity.preferred:
        if any(r.key == key for r in pref.preference.match_expressions):
            return True
    return False


def _mark_unschedulable(pod: Pod) -> None:
    """Pin the pod to a zone no offering can provide: zone feasibility is
    enforced by the instance-type offering filter for every catalog, unlike
    hostname, so this reliably drops (and logs) the pod at pack time."""
    _set_domain(pod, lbl.TOPOLOGY_ZONE, UNSATISFIABLE_DOMAIN)


def ignored_for_topology(p: Pod) -> bool:
    return not podutil.is_scheduled(p) or podutil.is_terminal(p) or podutil.is_terminating(p)
