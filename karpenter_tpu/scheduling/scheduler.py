"""Scheduler facade: dispatches a solve to the backend selected by the
provisioner's ``spec.solver`` field (the north-star seam from BASELINE.json —
the reconcile loop and launch path are backend-agnostic)."""

from __future__ import annotations

import copy
import random
import time
from typing import List, Optional, Sequence

from karpenter_tpu.api.provisioner import Provisioner, SOLVER_TPU
from karpenter_tpu.cloudprovider.requirements import catalog_requirements
from karpenter_tpu.cloudprovider.types import InstanceType
from karpenter_tpu.api.objects import Pod
from karpenter_tpu.kube.client import Cluster
from karpenter_tpu.scheduling.ffd import FFDScheduler, VirtualNode
from karpenter_tpu import metrics


class Scheduler:
    def __init__(
        self,
        cluster: Cluster,
        rng: Optional[random.Random] = None,
        solver_service_address: Optional[str] = None,
        pack_checksum: Optional[bool] = None,
        canary_rate: Optional[float] = None,
        solver_stream: Optional[bool] = None,
        solver_shm_dir: Optional[str] = None,
        solver_delta: Optional[bool] = None,
    ):
        self.cluster = cluster
        self.ffd = FFDScheduler(cluster, rng=rng)
        self._tpu = None  # built lazily: importing jax is not free
        self._rng = rng
        self._service_address = solver_service_address
        # corruption defense (docs/integrity.md): wire checksums + canary
        # cross-check rate, threaded to the TPU backend (None = env twins)
        self._pack_checksum = pack_checksum
        self._canary_rate = canary_rate
        # streaming transport + zero-copy shm arena toward the sidecar(s)
        # (docs/solver-transport.md § Streaming; None = env twins)
        self._solver_stream = solver_stream
        self._solver_shm_dir = solver_shm_dir
        # resident delta encoding (docs/delta-encoding.md; None = env twin)
        self._solver_delta = solver_delta

    def _tpu_scheduler(self):
        if self._tpu is None:
            from karpenter_tpu.solver.backend import TpuScheduler

            self._tpu = TpuScheduler(
                self.cluster, rng=self._rng, service_address=self._service_address,
                pack_checksum=self._pack_checksum,
                canary_rate=self._canary_rate,
                solver_stream=self._solver_stream,
                solver_shm_dir=self._solver_shm_dir,
                solver_delta=self._solver_delta,
            )
        return self._tpu

    def last_stage_profile(self) -> dict:
        """Per-stage timings of the most recent accelerated solve (sort /
        inject / encode / wire_ser / pack_fetch / wire_deser / decode
        seconds, plus packer_backend) — {} when the FFD backend served.
        The provisioning worker plumbs these into
        ``karpenter_solver_stage_duration_seconds`` after each batch.

        Reads the CALLING THREAD's completed profile (published atomically
        after a solve's final stage write; scheduler-wide latest as the
        fallback) — never the begin-published ``last_profile`` a
        concurrent solve may still be filling in, and never another
        worker's solve when the scheduler is shared."""
        if self._tpu is None:
            return {}
        return self._tpu.completed_profile()

    def last_decision_context(self) -> dict:
        """The calling thread's most recent accelerated solve's decision
        context (encoded batch + assignment + route provenance) for the
        decision audit log — CONSUMED on read, {} for the FFD backend or
        when the decision plane is disabled (docs/decisions.md)."""
        if self._tpu is None:
            return {}
        return self._tpu.completed_decision()

    def solve(
        self,
        provisioner: Provisioner,
        instance_types: Sequence[InstanceType],
        pods: Sequence[Pod],
    ) -> List[VirtualNode]:
        from karpenter_tpu import obs

        start = time.perf_counter()
        # Layer the live catalog's supported values into the constraints; the
        # provisioning controller also refreshes these at apply (reference:
        # provisioning/controller.go:104-106), but re-layering here is
        # idempotent and keeps the facade safe to call standalone.
        constraints = provisioner.spec.constraints.clone()
        constraints.requirements = constraints.requirements.merge(
            catalog_requirements(instance_types)
        )
        # the end-to-end solve span: what the flight recorder watches
        # against the 100ms budget, and the root the stage spans hang off
        with obs.tracer().span(
            "solver.solve",
            attrs={
                "provisioner": provisioner.name,
                "solver": provisioner.spec.solver,
                "pods": len(pods),
                "types": len(instance_types),
            },
        ) as sp:
            try:
                if provisioner.spec.solver == SOLVER_TPU:
                    nodes = self._tpu_scheduler().solve(
                        constraints, instance_types, pods
                    )
                else:
                    nodes = self.ffd.solve(constraints, instance_types, pods)
                sp.set_attribute("nodes", len(nodes))
                return nodes
            finally:
                metrics.SCHEDULING_DURATION.labels(
                    provisioner=provisioner.name
                ).observe(time.perf_counter() - start)
