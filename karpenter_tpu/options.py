"""Process options: flags with environment fallbacks.

Mirrors ``pkg/utils/options``: cluster identity, ports, client QPS/burst,
plus this framework's solver knobs; validated at startup
(reference: utils/options/options.go:34-89, utils/env/env.go).
"""

from __future__ import annotations

import argparse
import os
from dataclasses import dataclass, field
from typing import List, Optional


def _env(key: str, default: str) -> str:
    return os.environ.get(key, default)


def env_bool(key: str, default: bool = False) -> bool:
    """One spelling of the boolean env contract — shared with consumers
    that read the twin directly (e.g. TpuScheduler's integrity knobs), so
    the accepted literals can't drift between parsers."""
    return _env(key, "true" if default else "false").strip().lower() == "true"


def env_float(key: str, default: float = 0.0) -> float:
    raw = _env(key, "").strip()
    return float(raw) if raw else default


@dataclass
class Options:
    cluster_name: str = field(default_factory=lambda: _env("CLUSTER_NAME", ""))
    cluster_endpoint: str = field(default_factory=lambda: _env("CLUSTER_ENDPOINT", ""))
    metrics_port: int = field(default_factory=lambda: int(_env("METRICS_PORT", "8080")))
    health_probe_port: int = field(default_factory=lambda: int(_env("HEALTH_PROBE_PORT", "8081")))
    kube_client_qps: float = field(default_factory=lambda: float(_env("KUBE_CLIENT_QPS", "200")))
    kube_client_burst: int = field(default_factory=lambda: int(_env("KUBE_CLIENT_BURST", "300")))
    cloud_provider: str = field(default_factory=lambda: _env("CLOUD_PROVIDER", "fake"))
    # apiserver URL backing the Cluster; "" = in-memory store,
    # "in-cluster" = service-account config from the pod environment
    kube_api_server: str = field(default_factory=lambda: _env("KUBE_API_SERVER", ""))
    # solver knobs (new in this framework)
    default_solver: str = field(default_factory=lambda: _env("KARPENTER_SOLVER", "ffd"))
    # one sidecar address, or a comma-separated POOL of them (consistent-hash
    # session routing + per-member breakers + ring failover, solver/pool.py)
    solver_service_address: str = field(
        default_factory=lambda: _env("SOLVER_SERVICE_ADDRESS", "")
    )  # empty = in-process
    # streaming solver transport (docs/solver-transport.md § Streaming):
    # persistent multiplexed streams per sidecar (credit flow control,
    # out-of-order completion, transparent unary fallback). Off by
    # default like --pack-checksum; ON in deploy/chart — the capability
    # negotiation makes mixed-version fleets interop in either order.
    solver_stream: bool = field(
        default_factory=lambda: env_bool("KARPENTER_SOLVER_STREAM")
    )
    # zero-copy colocated fast path: a directory shared with the sidecar
    # (same host) through which pod-side arrays move as an mmap'd arena —
    # the stream then carries offsets, not bytes. '' disables.
    solver_shm_dir: str = field(
        default_factory=lambda: _env("KARPENTER_SOLVER_SHM_DIR", "")
    )
    # resident delta encoding (docs/delta-encoding.md): keep the encoded
    # cluster resident across rounds and patch it from per-round deltas,
    # epoch-guarded so staleness fails loud into a full re-encode. Off by
    # default like --solver-stream; ON in deploy/chart.
    solver_delta: bool = field(
        default_factory=lambda: env_bool("KARPENTER_SOLVER_DELTA")
    )
    consolidation_enabled: bool = field(
        default_factory=lambda: env_bool("KARPENTER_CONSOLIDATION")
    )
    # evict-mode retirement pacing: nodes retired per reconcile wave
    consolidation_wave_size: int = field(
        default_factory=lambda: int(_env("KARPENTER_CONSOLIDATION_WAVE_SIZE", "5"))
    )
    # controller-level default disruption budget for provisioners that
    # leave spec.disruptionBudget unset: a count ("3") or percent ("20%")
    # of a provisioner's nodes disruptable at once across settling waves;
    # "0" disables voluntary disruption, "" = no budget (wave size paces)
    consolidation_budget: str = field(
        default_factory=lambda: _env("KARPENTER_CONSOLIDATION_BUDGET", "")
    )
    # leader election: path to a shared lease file; empty = single-process,
    # no election (reference: cmd/controller/main.go:84-85)
    leader_election_lease: str = field(
        default_factory=lambda: _env("LEADER_ELECTION_LEASE", "")
    )
    # fleet sharding (docs/fleet.md): per-provisioner shard leases instead
    # of (not alongside) whole-process leader election. A shared lease-set
    # file path, or kube:<namespace>/<prefix> for Lease objects; empty =
    # this replica owns every provisioner.
    shard_lease: str = field(default_factory=lambda: _env("SHARD_LEASE", ""))
    shard_lease_duration: float = field(
        default_factory=lambda: float(_env("SHARD_LEASE_DURATION", "15"))
    )
    # write-ahead launch journal (docs/launch-journal.md): a shared file
    # path, kube:<namespace>/<prefix> for apiserver-durable Lease twins, or
    # memory: for tests; empty = journaling off (creates still carry
    # tokens, but a crashed launch leaves no breadcrumb to adopt from)
    launch_journal: str = field(default_factory=lambda: _env("LAUNCH_JOURNAL", ""))
    # orphan-instance GC sweep cadence and the age past which an untracked,
    # unjournaled instance is declared a leak and terminated
    gc_interval: float = field(
        default_factory=lambda: float(_env("KARPENTER_GC_INTERVAL", "30"))
    )
    gc_grace_period: float = field(
        default_factory=lambda: float(_env("KARPENTER_GC_GRACE_PERIOD", "120"))
    )
    # live log-level reload source (the mounted config-logging key); empty =
    # static level from LOG_LEVEL
    log_config_file: str = field(default_factory=lambda: _env("LOG_CONFIG_FILE", ""))
    log_level: str = field(default_factory=lambda: _env("LOG_LEVEL", "info"))
    # end-to-end tracing (karpenter_tpu/obs): span pipeline + /debug/traces
    trace_enabled: bool = field(
        default_factory=lambda: env_bool("KARPENTER_TRACE", default=True)
    )
    # slow-solve flight recorder: capped on-disk ring of over-budget solve
    # traces + router/breaker/session state; empty = disabled
    flight_dir: str = field(default_factory=lambda: _env("KARPENTER_FLIGHT_DIR", ""))
    flight_budget_ms: float = field(
        default_factory=lambda: float(_env("KARPENTER_FLIGHT_BUDGET_MS", "100"))
    )
    # online SLO engine (obs/slo.py): fast evaluation window in seconds
    # (the slow burn-rate window is 12x this), and an optional objectives
    # file ('' = the built-in defaults; docs/observability.md has the
    # grammar)
    slo_window: float = field(
        default_factory=lambda: float(_env("KARPENTER_SLO_WINDOW", "300"))
    )
    slo_config: str = field(default_factory=lambda: _env("KARPENTER_SLO_CONFIG", ""))
    # pack integrity (docs/integrity.md): per-frame checksums on the v3
    # solver wire (capability-gated — off keeps the wire byte-identical),
    # and the fraction of device/pool solves re-solved on the in-process
    # native packer and compared (0 disables the canary)
    pack_checksum: bool = field(
        default_factory=lambda: env_bool("KARPENTER_PACK_CHECKSUM")
    )
    canary_rate: float = field(
        default_factory=lambda: env_float("KARPENTER_CANARY_RATE")
    )
    # always-on sampling profiler (obs/profiler.py): stack-sample rate in
    # Hz (0 disables; 19 is deliberately off-aligned from 10/20/100Hz
    # periodic work). Served at GET /debug/profile; self-accounted cost
    # rides karpenter_telemetry_profile_overhead_ratio.
    profile_hz: float = field(
        default_factory=lambda: float(_env("KARPENTER_PROFILE_HZ", "19"))
    )
    # fleet telemetry plane (obs/collector.py, docs/telemetry.md):
    # - telemetry_dir: shared flock'd directory every member (controller
    #   replicas + sidecars) flushes span trees / SLO histograms / profile
    #   folds into; '' = no file backend
    # - telemetry_peers: comma-separated [name=]http://host:port entries
    #   whose /debug/* endpoints the collector scrapes (pull mode, no
    #   shared volume needed)
    # GET /debug/fleet serves the aggregate when either is set.
    telemetry_dir: str = field(default_factory=lambda: _env("KARPENTER_TELEMETRY_DIR", ""))
    telemetry_peers: str = field(
        default_factory=lambda: _env("KARPENTER_TELEMETRY_PEERS", "")
    )
    telemetry_flush_interval: float = field(
        default_factory=lambda: float(_env("KARPENTER_TELEMETRY_FLUSH", "10"))
    )
    # decision observability plane (obs/decisions.py, docs/decisions.md):
    # - explain_enabled: per-round decision records + elimination
    #   attribution (--no-explain turns the whole plane off; the bench
    #   overhead gate measures the delta)
    # - decision_dir: capped on-disk ring of REPLAYABLE decision records
    #   (tools/replay_decision.py re-solves them offline); '' keeps the
    #   memory-only ring backing /debug/decisions and /debug/explain
    # - unschedulable_event_rounds: consecutive failed rounds before a pod
    #   gets its PodUnschedulable Warning event
    explain_enabled: bool = field(
        default_factory=lambda: env_bool("KARPENTER_EXPLAIN", default=True)
    )
    decision_dir: str = field(
        default_factory=lambda: _env("KARPENTER_DECISION_DIR", "")
    )
    unschedulable_event_rounds: int = field(
        default_factory=lambda: int(
            _env("KARPENTER_UNSCHEDULABLE_EVENT_ROUNDS", "3")
        )
    )
    # SLO-driven brownout ladder (resilience/brownout.py): when an
    # objective burns, walk the ordered degradation ladder (pause probes/
    # consolidation -> shrink admission window -> bias native -> shed
    # low-priority queue) instead of letting the queues decide what drops
    brownout_enabled: bool = field(
        default_factory=lambda: env_bool("KARPENTER_BROWNOUT", default=True)
    )
    brownout_interval: float = field(
        default_factory=lambda: float(_env("KARPENTER_BROWNOUT_INTERVAL", "5"))
    )
    # predictive provisioning (karpenter_tpu/forecast/, docs/forecasting.md):
    # - warm_pool: the speculative warm-pool controller (launch ahead of
    #   forecast demand; the provisioning worker claims warm nodes before
    #   solving). Requires the arrival forecaster, which is always on.
    # - warm_pool_ttl: seconds an unclaimed speculative node may stand
    #   before the GC replay ladder reclaims it
    # - warm_pool_max_nodes: per-provisioner standing-pool ceiling
    # - forecast_model: ewma | holt-winters (the seasonal option)
    # - forecast_alpha: EWMA/Holt-Winters level smoothing factor
    warm_pool: bool = field(
        default_factory=lambda: env_bool("KARPENTER_WARM_POOL")
    )
    warm_pool_ttl: float = field(
        default_factory=lambda: float(_env("KARPENTER_WARM_POOL_TTL", "600"))
    )
    warm_pool_max_nodes: int = field(
        default_factory=lambda: int(_env("KARPENTER_WARM_POOL_MAX_NODES", "10"))
    )
    forecast_model: str = field(
        default_factory=lambda: _env("KARPENTER_FORECAST_MODEL", "ewma")
    )
    forecast_alpha: float = field(
        default_factory=lambda: float(_env("KARPENTER_FORECAST_ALPHA", "0.3"))
    )
    # regression sentinel (obs/sentinel.py, docs/observability.md):
    # online per-(stage, route, shape) latency baselines off the span
    # stream + change-point detection; sustained deviations mint
    # correlated incident records at /debug/incidents. sentinel_dir
    # persists the baseline table across restarts ('' = memory-only,
    # re-learns each boot).
    sentinel_enabled: bool = field(
        default_factory=lambda: env_bool("KARPENTER_SENTINEL", default=True)
    )
    sentinel_dir: str = field(
        default_factory=lambda: _env("KARPENTER_SENTINEL_DIR", "")
    )

    def validate(self) -> List[str]:
        errs = []
        if self.metrics_port <= 0 or self.metrics_port > 65535:
            errs.append(f"metrics port {self.metrics_port} out of range")
        if self.health_probe_port <= 0 or self.health_probe_port > 65535:
            errs.append(f"health probe port {self.health_probe_port} out of range")
        if self.kube_client_qps <= 0:
            errs.append("kube client QPS must be positive")
        if self.kube_client_burst <= 0:
            errs.append("kube client burst must be positive")
        if self.consolidation_wave_size <= 0:
            errs.append("consolidation wave size must be positive")
        if self.consolidation_budget:
            from karpenter_tpu.controllers.disruption import parse_budget

            try:
                parse_budget(self.consolidation_budget)
            except ValueError as e:
                errs.append(f"consolidation budget: {e}")
        if self.shard_lease_duration <= 0:
            errs.append("shard lease duration must be positive seconds")
        if self.gc_interval <= 0:
            errs.append("GC interval must be positive seconds")
        if self.gc_grace_period <= 0:
            errs.append("GC grace period must be positive seconds")
        if self.shard_lease and self.leader_election_lease:
            errs.append(
                "shard leases replace leader election — set only one of "
                "--shard-lease / --leader-election-lease"
            )
        if self.flight_budget_ms <= 0:
            errs.append("flight budget must be positive milliseconds")
        if self.slo_window <= 0:
            errs.append("SLO window must be positive seconds")
        if self.brownout_interval <= 0:
            errs.append("brownout tick interval must be positive seconds")
        if self.warm_pool_ttl <= 0:
            errs.append("warm-pool TTL must be positive seconds")
        if self.warm_pool_max_nodes < 1:
            errs.append("warm-pool max nodes must be >= 1")
        if self.forecast_model not in ("ewma", "holt-winters"):
            errs.append(
                f"forecast model must be ewma|holt-winters, got {self.forecast_model}"
            )
        if not 0.0 < self.forecast_alpha <= 1.0:
            errs.append("forecast alpha must be a fraction in (0, 1]")
        if self.unschedulable_event_rounds < 1:
            errs.append("unschedulable event rounds must be >= 1")
        if not 0.0 <= self.profile_hz <= 250.0:
            errs.append("profiler rate must be 0 (off) to 250 Hz")
        if self.telemetry_flush_interval <= 0:
            errs.append("telemetry flush interval must be positive seconds")
        if not 0.0 <= self.canary_rate <= 1.0:
            errs.append("canary rate must be a fraction in [0, 1]")
        if self.slo_config:
            # a typo'd objective must fail startup, not silently never
            # evaluate — parse the whole file eagerly
            try:
                from karpenter_tpu.obs.slo import load_objectives

                load_objectives(self.slo_config)
            except Exception as e:
                errs.append(f"--slo-config {self.slo_config}: {e}")
        if self.default_solver not in ("ffd", "tpu"):
            errs.append(f"solver must be ffd|tpu, got {self.default_solver}")
        from karpenter_tpu.logging_config import validate_log_config

        err = validate_log_config(self.log_level)
        if err:
            errs.append(err)
        return errs


def parse_args(argv: Optional[List[str]] = None) -> Options:
    opts = Options()
    ap = argparse.ArgumentParser(prog="karpenter-tpu")
    ap.add_argument("--cluster-name", default=opts.cluster_name)
    ap.add_argument("--cluster-endpoint", default=opts.cluster_endpoint)
    ap.add_argument("--metrics-port", type=int, default=opts.metrics_port)
    ap.add_argument("--health-probe-port", type=int, default=opts.health_probe_port)
    # --kube-qps/--kube-burst: the client-go-style flow-control spellings
    # (docs/partition.md) — same knobs, feeding the transport's
    # mutation-priority token bucket (kube/transport.py)
    ap.add_argument(
        "--kube-client-qps", "--kube-qps", type=float,
        default=opts.kube_client_qps,
        help="client-side apiserver flow control: sustained requests/sec "
        "(mutations are prioritized over reads inside the bucket)",
    )
    ap.add_argument(
        "--kube-client-burst", "--kube-burst", type=int,
        default=opts.kube_client_burst,
        help="client-side apiserver flow control: burst bucket size",
    )
    ap.add_argument("--cloud-provider", default=opts.cloud_provider)
    ap.add_argument("--kube-api-server", default=opts.kube_api_server,
                    help="apiserver URL ('' = in-memory store, 'in-cluster' = pod env)")
    ap.add_argument("--default-solver", default=opts.default_solver)
    ap.add_argument("--solver-service-address", default=opts.solver_service_address)
    ap.add_argument(
        "--solver-stream",
        action=argparse.BooleanOptionalAction,
        default=opts.solver_stream,
        help="persistent multiplexed solve streams toward the sidecar(s): "
        "credit flow control, out-of-order completion, transparent unary "
        "fallback (capability-gated on PROTO_STREAM, so mixed-version "
        "fleets interop; docs/solver-transport.md)",
    )
    ap.add_argument(
        "--solver-delta",
        action=argparse.BooleanOptionalAction,
        default=opts.solver_delta,
        help="resident delta encoding: keep the encoded cluster resident "
        "across rounds (host tensors + the sidecar's wire store) and ship "
        "per-round deltas instead of re-encoding from scratch; "
        "epoch-guarded — staleness forces a counted full re-encode "
        "(capability-gated on PROTO_DELTA for the wire side, so "
        "mixed-version fleets interop; docs/delta-encoding.md)",
    )
    ap.add_argument(
        "--solver-shm-dir", default=opts.solver_shm_dir,
        help="zero-copy colocated fast path: a directory shared with the "
        "sidecar on the same host; pod arrays move via an mmap'd arena "
        "and the stream carries only offsets ('' disables)",
    )
    ap.add_argument("--leader-election-lease", default=opts.leader_election_lease)
    ap.add_argument(
        "--shard-lease", default=opts.shard_lease,
        help="fleet sharding: lease-set file path or kube:<ns>/<prefix> "
        "('' = this replica owns every provisioner; replaces leader election)",
    )
    ap.add_argument(
        "--shard-lease-duration", type=float, default=opts.shard_lease_duration,
        help="seconds a shard lease lives without renewal (failover "
        "completes within ~2x this)",
    )
    ap.add_argument(
        "--launch-journal", default=opts.launch_journal,
        help="write-ahead launch journal: shared file path, kube:<ns>/<prefix>, "
        "or memory: ('' disables; docs/launch-journal.md)",
    )
    ap.add_argument(
        "--gc-interval", type=float, default=opts.gc_interval,
        help="orphan-instance GC sweep cadence in seconds (adoption "
        "completes within one period)",
    )
    ap.add_argument(
        "--gc-grace-period", type=float, default=opts.gc_grace_period,
        help="age past which an untracked, unjournaled instance is "
        "terminated as a leak",
    )
    ap.add_argument("--log-config-file", default=opts.log_config_file)
    ap.add_argument("--log-level", default=opts.log_level)
    ap.add_argument(
        "--trace",
        action=argparse.BooleanOptionalAction,
        default=opts.trace_enabled,
        help="end-to-end span tracing (--no-trace disables; /debug/traces "
        "on the health port serves the ring)",
    )
    ap.add_argument(
        "--flight-dir", default=opts.flight_dir,
        help="capped on-disk ring for slow-solve flight records "
        "('' disables; served at GET /debug/flight)",
    )
    ap.add_argument(
        "--flight-budget-ms", type=float, default=opts.flight_budget_ms,
        help="solver.solve spans over this budget are flight-recorded",
    )
    ap.add_argument(
        "--slo-window", type=float, default=opts.slo_window,
        help="online SLO fast evaluation window in seconds (the slow "
        "burn-rate window is 12x this; /debug/slo serves the verdicts)",
    )
    ap.add_argument(
        "--slo-config", default=opts.slo_config,
        help="objectives file, one `source.stat op value` line each "
        "('' = built-in defaults; docs/observability.md has the grammar)",
    )
    ap.add_argument(
        "--pack-checksum",
        action=argparse.BooleanOptionalAction,
        default=opts.pack_checksum,
        help="end-to-end frame checksums on the v3 solver wire "
        "(capability-gated on PROTO_CHECKSUM, so mixed-version fleets "
        "interop; a mismatch quarantines the member — docs/integrity.md)",
    )
    ap.add_argument(
        "--canary-rate", type=float, default=opts.canary_rate,
        help="fraction of device/pool solves re-solved on the in-process "
        "native packer off the hot path and compared; a mismatch "
        "quarantines the serving member (0 disables; pauses while the "
        "brownout ladder has probes paused)",
    )
    ap.add_argument(
        "--profile-hz", type=float, default=opts.profile_hz,
        help="sampling-profiler stack-sample rate in Hz (0 disables; "
        "GET /debug/profile serves the folds, docs/telemetry.md)",
    )
    ap.add_argument(
        "--telemetry-dir", default=opts.telemetry_dir,
        help="shared directory the fleet telemetry plane flushes member "
        "payloads into ('' disables the file backend; docs/telemetry.md)",
    )
    ap.add_argument(
        "--telemetry-peers", default=opts.telemetry_peers,
        help="comma-separated [name=]http://host:port member endpoints the "
        "collector scrapes (pull mode); GET /debug/fleet serves the "
        "aggregate",
    )
    ap.add_argument(
        "--telemetry-flush-interval", type=float,
        default=opts.telemetry_flush_interval,
        help="seconds between member telemetry flushes",
    )
    ap.add_argument(
        "--explain",
        action=argparse.BooleanOptionalAction,
        default=opts.explain_enabled,
        help="per-pod decision observability: round decision records + "
        "elimination attribution (--no-explain disables the plane; "
        "/debug/decisions and /debug/explain serve it — docs/decisions.md)",
    )
    ap.add_argument(
        "--decision-dir", default=opts.decision_dir,
        help="capped on-disk ring of replayable decision records "
        "('' = memory-only; tools/replay_decision.py re-solves a "
        "persisted record offline and diffs the assignment)",
    )
    ap.add_argument(
        "--unschedulable-event-rounds", type=int,
        default=opts.unschedulable_event_rounds,
        help="consecutive failed selection/placement rounds before a pod "
        "gets a PodUnschedulable Warning event carrying its top "
        "elimination reason and the decision id",
    )
    ap.add_argument(
        "--brownout",
        action=argparse.BooleanOptionalAction,
        default=opts.brownout_enabled,
        help="SLO-driven brownout ladder: degrade deferrable work in order "
        "while an objective burns (--no-brownout disables; docs/overload.md)",
    )
    ap.add_argument(
        "--brownout-interval", type=float, default=opts.brownout_interval,
        help="seconds between brownout ladder evaluations",
    )
    ap.add_argument(
        "--warm-pool",
        action=argparse.BooleanOptionalAction,
        default=opts.warm_pool,
        help="speculative warm-pool provisioning: launch nodes ahead of "
        "forecast demand and claim them before solving "
        "(docs/forecasting.md; pauses at brownout rung 1)",
    )
    ap.add_argument(
        "--warm-pool-ttl", type=float, default=opts.warm_pool_ttl,
        help="seconds an unclaimed speculative node may stand before the "
        "GC replay ladder reclaims it",
    )
    ap.add_argument(
        "--warm-pool-max-nodes", type=int, default=opts.warm_pool_max_nodes,
        help="per-provisioner ceiling on standing warm-pool nodes",
    )
    ap.add_argument(
        "--forecast-model", default=opts.forecast_model,
        help="arrival-rate forecaster model: ewma | holt-winters "
        "(the additive-seasonal option)",
    )
    ap.add_argument(
        "--forecast-alpha", type=float, default=opts.forecast_alpha,
        help="forecaster level smoothing factor in (0, 1]",
    )
    ap.add_argument(
        "--sentinel",
        action=argparse.BooleanOptionalAction,
        default=opts.sentinel_enabled,
        help="regression sentinel: online latency baselines per (stage, "
        "route, shape) + change-point detection over the span stream; "
        "sustained deviations mint correlated incident records "
        "(--no-sentinel disables; /debug/incidents serves them — "
        "docs/observability.md)",
    )
    ap.add_argument(
        "--sentinel-dir", default=opts.sentinel_dir,
        help="directory the sentinel persists its learned baselines into "
        "so a restart resumes instead of re-learning ('' = memory-only)",
    )
    ap.add_argument(
        "--consolidation",
        action=argparse.BooleanOptionalAction,
        default=opts.consolidation_enabled,
        help="enable the consolidation (cost-optimal deprovisioning) controller"
        " (--no-consolidation overrides KARPENTER_CONSOLIDATION=true)",
    )
    ap.add_argument(
        "--consolidation-wave-size", type=int,
        default=opts.consolidation_wave_size,
        help="evict-mode pacing: nodes retired per consolidation wave",
    )
    ap.add_argument(
        "--consolidation-budget", default=opts.consolidation_budget,
        help="default disruption budget for provisioners without "
        "spec.disruptionBudget: a count ('3') or percent ('20%%') of "
        "nodes disruptable at once; '0' disables voluntary disruption, "
        "'' = unbudgeted (docs/consolidation.md)",
    )
    ns = ap.parse_args(argv)
    out = Options(
        cluster_name=ns.cluster_name,
        cluster_endpoint=ns.cluster_endpoint,
        metrics_port=ns.metrics_port,
        health_probe_port=ns.health_probe_port,
        kube_client_qps=ns.kube_client_qps,
        kube_client_burst=ns.kube_client_burst,
        cloud_provider=ns.cloud_provider,
        kube_api_server=ns.kube_api_server,
        default_solver=ns.default_solver,
        solver_service_address=ns.solver_service_address,
        solver_stream=ns.solver_stream,
        solver_delta=ns.solver_delta,
        solver_shm_dir=ns.solver_shm_dir,
        consolidation_enabled=ns.consolidation,
        consolidation_wave_size=ns.consolidation_wave_size,
        consolidation_budget=ns.consolidation_budget,
        leader_election_lease=ns.leader_election_lease,
        shard_lease=ns.shard_lease,
        shard_lease_duration=ns.shard_lease_duration,
        launch_journal=ns.launch_journal,
        gc_interval=ns.gc_interval,
        gc_grace_period=ns.gc_grace_period,
        log_config_file=ns.log_config_file,
        log_level=ns.log_level,
        trace_enabled=ns.trace,
        flight_dir=ns.flight_dir,
        flight_budget_ms=ns.flight_budget_ms,
        slo_window=ns.slo_window,
        slo_config=ns.slo_config,
        pack_checksum=ns.pack_checksum,
        canary_rate=ns.canary_rate,
        profile_hz=ns.profile_hz,
        telemetry_dir=ns.telemetry_dir,
        telemetry_peers=ns.telemetry_peers,
        telemetry_flush_interval=ns.telemetry_flush_interval,
        brownout_enabled=ns.brownout,
        brownout_interval=ns.brownout_interval,
        warm_pool=ns.warm_pool,
        warm_pool_ttl=ns.warm_pool_ttl,
        warm_pool_max_nodes=ns.warm_pool_max_nodes,
        forecast_model=ns.forecast_model,
        forecast_alpha=ns.forecast_alpha,
        explain_enabled=ns.explain,
        decision_dir=ns.decision_dir,
        unschedulable_event_rounds=ns.unschedulable_event_rounds,
        sentinel_enabled=ns.sentinel,
        sentinel_dir=ns.sentinel_dir,
    )
    errs = out.validate()
    if errs:
        ap.error("; ".join(errs))
    return out
